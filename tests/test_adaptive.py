"""Feedback-driven adaptive execution (plan/stats.py, docs/adaptive.md).

Covers the whole loop: stats round-trip + LRU bounds, the observed-
cardinality build-side flip (with verify_rewrite passing), cap seeding
across executor instances (zero escalation retries + a jit-cache hit on
the warm path), the kernel registry's stats tie-break and its
KernelChoice stamp, JSONL persistence on/off, stale-stats safety
(schema-changed fingerprints never match), and backend isolation — a
degraded (CPU-salvaged) run's stats must never drive device-side
decisions.

The suite-wide default is SPARK_RAPIDS_TPU_STATS=off (conftest):
everything here installs an explicit `scoped_store`, which outranks the
knob, so these tests are order-independent and leak nothing.
"""
import json

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_tpu import Column, Table, dtypes, faultinj
from spark_rapids_tpu.plan import (PlanBuilder, PlanExecutor, StatsStore,
                                   col, scoped_store,
                                   subtree_fingerprints)
from spark_rapids_tpu.plan import stats as stats_mod


def _col(a):
    a = np.asarray(a, dtype=np.int64)
    return Column(dtype=dtypes.INT64, length=len(a), data=jnp.asarray(a))


def _skew_tables(n_big=1000, n_small=1000, seed=0):
    """The skewed-join shape: the filtered side's static 0.5-selectivity
    estimate is WRONG (the filter actually keeps ~1%), so the static
    build-side rule keeps while observations swap."""
    rng = np.random.default_rng(seed)
    big = Table([_col(rng.integers(0, 10, n_big)),
                 _col(rng.integers(0, 100, n_big))], names=["k", "v"])
    small = Table([_col(rng.integers(0, 100, n_small)),
                   _col(rng.integers(0, 100, n_small))],
                  names=["sk", "sv"])
    return {"small": small, "big": big}


def _skew_plan():
    b = PlanBuilder()
    # est_rows hints mirror the bound sizes — deliberately useless: the
    # misestimate the store corrects is the FILTER's selectivity, which
    # no scan hint can express
    left = b.scan("small", schema=["sk", "sv"],
                  est_rows=1000).filter(col("sv") == 0)
    right = b.scan("big", schema=["k", "v"], est_rows=1000)
    return (left.join(right, left_on="sk", right_on="k")
                .aggregate(["sv"], [("v", "sum", "total")])
                .build())


def _fanout_tables(seed=0):
    rng = np.random.default_rng(seed)
    l = Table([_col(rng.integers(0, 20, 400)),
               _col(rng.integers(0, 100, 400))], names=["k", "v"])
    r = Table([_col(rng.integers(0, 20, 100))], names=["rk"])
    return {"l": l, "r": r}


def _fanout_plan():
    b = PlanBuilder()
    return (b.scan("l", schema=["k", "v"])
             .join(b.scan("r", schema=["rk"]), left_on="k", right_on="rk")
             .aggregate(["k"], [("v", "sum", "t")])
             .build())


# ---- store round-trip + bounds ----------------------------------------------

def test_store_round_trip_and_evict():
    store = StatsStore(capacity=2, path="")
    plans = []
    for n_cols in (2, 3, 4):        # three distinct fingerprints
        b = PlanBuilder()
        names = [f"c{i}" for i in range(n_cols)]
        plans.append(b.scan(f"s{n_cols}", schema=names)
                      .aggregate([names[0]], [(names[1], "sum", "t")])
                      .build())
    last = None
    with scoped_store(store):
        for p, n_rows in zip(plans, (40, 60, 80)):
            t = Table([_col(np.arange(n_rows) % 5)
                       for _ in range(len(p.scans[0].schema))],
                      names=list(p.scans[0].schema))
            last = PlanExecutor(mode="eager").execute(
                p, {p.scans[0].source: t})
    # lookup: the two most recent plan entries survive, the first evicted
    backend = "cpu"
    assert store.plan_runs(backend, plans[0].fingerprint) == 0
    assert store.plan_runs(backend, plans[1].fingerprint) == 1
    assert store.plan_runs(backend, plans[2].fingerprint) == 1
    # subtree observations round-trip with exact cardinalities — keyed by
    # the EXECUTED (optimizer-rewritten) plan's subtrees, which is what
    # the next optimization's fixpoint pass converges to and consults
    sub = subtree_fingerprints(last.plan.root)
    got = store.observed_rows(backend, sub[id(last.plan.root)])
    assert got is not None and got[0] == 5 and got[1] == 1  # 5 groups
    # per-op history round-trips too (the co-placement input surface)
    ops = store.op_stats(backend, plans[2].fingerprint)
    root_idx = len(last.plan.nodes) - 1
    assert ops[root_idx]["rows_out"] == 5
    assert ops[root_idx]["wall_ms"] is not None


# ---- observed-cardinality build-side flip -----------------------------------

def test_observed_build_side_flip_with_verified_rewrite():
    plan = _skew_plan()
    inputs = _skew_tables()
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        cold = PlanExecutor(mode="eager").execute(plan, dict(inputs))
        assert not cold.optimizer["rules_fired"].get("build_side"), \
            "static estimates must NOT swap this join (the test's premise)"
        warm = PlanExecutor(mode="eager").execute(plan, dict(inputs))
    assert warm.optimizer["rules_fired"].get("build_side") == 1
    # decision provenance: the swap names the store as its source
    swaps = [v for k, v in warm.optimizer["decision_sources"].items()
             if k.endswith("/build_side") and v.startswith("swap")]
    assert swaps and "observed:1" in swaps[0]
    assert warm.optimizer["stats_driven"] is True
    # the rewrite passed the verify gate (VERIFY_PLANS is on suite-wide;
    # a violation would have raised) and did not fall back or revert
    assert not warm.optimizer["fell_back"]
    assert not warm.optimizer["stats_reverted"]
    # adaptivity changed HOW, never WHAT
    assert warm.compact().to_pydict() == cold.compact().to_pydict()


def test_stats_off_restores_static_decisions():
    plan = _skew_plan()
    inputs = _skew_tables()
    store = StatsStore(capacity=8, path="")
    with scoped_store(None):
        static = PlanExecutor(mode="eager").execute(plan, dict(inputs))
    with scoped_store(store):
        for _ in range(2):          # warm the store past the flip point
            PlanExecutor(mode="eager").execute(plan, dict(inputs))
    # a scoped None forces adaptivity off (the SPARK_RAPIDS_TPU_STATS=off
    # path) even though the store above holds flip-inducing observations:
    # byte-identical optimizer decisions to the never-recorded run
    with scoped_store(None):
        off = PlanExecutor(mode="eager").execute(plan, dict(inputs))
    assert off.optimizer == static.optimizer
    assert off.compact().to_pydict() == static.compact().to_pydict()


# ---- cap seeding ------------------------------------------------------------

def test_cap_seeding_skips_escalation_ladder():
    plan = _fanout_plan()
    inputs = _fanout_tables()
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        cold_ex = PlanExecutor(mode="capped")
        cold = cold_ex.execute(plan, dict(inputs))
        assert cold.attempts > 1, \
            "fan-out join must overflow the default caps (test premise)"
        # a FRESH executor: only the store carries the escalated caps
        warm_ex = PlanExecutor(mode="capped")
        warm = warm_ex.execute(plan, dict(inputs))
        assert warm.attempts == 1          # zero cap-escalation retries
        assert warm.caps == cold.caps      # seeded at the high-water
        # the seeded caps land on the same fingerprint-keyed program, so
        # the next execute is a pure jit-cache hit
        again = warm_ex.execute(plan, dict(inputs))
        assert again.attempts == 1 and again.jit_cache_hits >= 1
    assert cold.compact().to_pydict() == warm.compact().to_pydict() \
        == again.compact().to_pydict()
    # stats off: the static ladder is back (fresh executor, no memo)
    with scoped_store(None):
        static = PlanExecutor(mode="capped").execute(plan, dict(inputs))
    assert static.attempts == cold.attempts


# ---- kernel tie-break -------------------------------------------------------

def test_kernel_tie_break_demotion_and_stamp():
    from spark_rapids_tpu.ops.registry import KernelRegistry, Signature
    reg = KernelRegistry()
    reg.register("fuzzop", "xla", fn=lambda: "xla", fallback=True)
    reg.register("fuzzop", "fancy", fn=lambda: "fancy", backends=("*",))
    sig = Signature.of(extras_tier="eager")
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        # cold: the non-fallback candidate wins the rank order
        choice = reg.select("fuzzop", sig, backend="tpu")
        assert choice.name == "fancy" and not choice.stats_demoted
        # observed: fancy benches 5x slower than the fallback
        store.record_kernel("tpu", "fuzzop", sig, "fancy", 5.0)
        store.record_kernel("tpu", "fuzzop", sig, "xla", 1.0)
        choice = reg.select("fuzzop", sig, backend="tpu")
        assert choice.name == "xla" and choice.stats_demoted
        assert any(name == "fancy" and "stats" in why
                   for name, why in choice.declined)
        # a different signature is a different shape: no demotion
        other = Signature.of(extras_tier="capped")
        assert reg.select("fuzzop", other, backend="tpu").name == "fancy"
        # no signature at the call site: selection stays static
        assert not reg.select("fuzzop", None, backend="tpu").stats_demoted
    # store out of scope: selection is static again
    assert reg.select("fuzzop", sig, backend="tpu").name == "fancy"


def test_kernel_tie_break_hysteresis():
    from spark_rapids_tpu.ops.registry import KernelRegistry, Signature
    reg = KernelRegistry()
    reg.register("fuzzop2", "xla", fn=lambda: 0, fallback=True)
    reg.register("fuzzop2", "fancy", fn=lambda: 1, backends=("*",))
    sig = Signature.of()
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        # 10% slower is inside the hysteresis margin: noise must not
        # flap the pick (and with it the capped tier's compiled programs)
        store.record_kernel("tpu", "fuzzop2", sig, "fancy", 1.1)
        store.record_kernel("tpu", "fuzzop2", sig, "xla", 1.0)
        assert reg.select("fuzzop2", sig, backend="tpu").name == "fancy"


def test_kernel_epoch_bumps_on_verdict_flip_without_reorder():
    """Regression: the capped tier's jit-cache key relies on
    `kernel_epoch` capturing every demotion-verdict change. An EWMA
    drift can cross the 1.25x margin WITHOUT changing the raw timing
    order — the epoch must still bump, or a compiled program keyed on
    the old epoch would keep serving the now-demoted kernel."""
    from spark_rapids_tpu.ops.registry import Signature
    store = StatsStore(capacity=8, path="")
    sig = Signature.of()
    store.record_kernel("tpu", "op", sig, "xla", 1.0)
    store.record_kernel("tpu", "op", sig, "fancy", 1.2)   # inside margin
    assert store.kernel_slower("tpu", "op", sig, "fancy", "xla") is None
    epoch = store.kernel_epoch
    # EWMA moves 1.2 -> 1.6: order unchanged (fancy was already slower),
    # but the verdict flips to demoted — the epoch must notice
    store.record_kernel("tpu", "op", sig, "fancy", 2.0)
    assert store.kernel_slower("tpu", "op", sig, "fancy", "xla") \
        is not None
    assert store.kernel_epoch > epoch


def test_fresh_store_ignores_env_persistence_path(tmp_path, monkeypatch):
    """Regression: isolated stores (the fuzzer's per-case stores, the
    adaptive bench's cold/warm pair, these tests) pass path="" and must
    neither load nor write SPARK_RAPIDS_TPU_STATS_PATH — a persisted
    file would pre-warm a run that documents itself as cold."""
    path = tmp_path / "operator.jsonl"
    monkeypatch.setenv("SPARK_RAPIDS_TPU_STATS_PATH", str(path))
    with scoped_store(StatsStore(capacity=8, path=str(path))):
        PlanExecutor(mode="eager").execute(_fanout_plan(),
                                           _fanout_tables())
    written = path.read_text()                # simulated operator state
    fresh = StatsStore(capacity=8, path="")
    assert fresh.path is None and fresh.generation == 0
    with scoped_store(fresh):
        PlanExecutor(mode="eager").execute(_fanout_plan(),
                                           _fanout_tables())
    assert path.read_text() == written        # nothing appended
    # while a path=None store DOES adopt the knob (the process default)
    assert StatsStore(capacity=8).path == str(path)


def test_eager_run_records_kernel_timings():
    b = PlanBuilder()
    plan = (b.scan("t", schema=["a", "b"])
             .filter(col("a") > 2)
             .project([("a", col("a"))])
             .build())            # select_fusion -> FusedSelect dispatch
    t = Table([_col(np.arange(50) % 7), _col(np.arange(50))],
              names=["a", "b"])
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        res = PlanExecutor(mode="eager").execute(plan, {"t": t})
    assert any(m.kernel.endswith(":fused_select")
               for m in res.metrics.values())
    assert any(key[1] == "fused_select" for key in store._kernels), \
        "eager per-op wall should feed the kernel-timing table"


# ---- persistence ------------------------------------------------------------

def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "stats.jsonl")
    plan = _fanout_plan()
    inputs = _fanout_tables()
    st1 = StatsStore(capacity=8, path=path)
    with scoped_store(st1):
        res = PlanExecutor(mode="capped").execute(plan, dict(inputs))
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert lines and lines[0]["backend"] == "cpu"
    # a NEW store replays the file: the warm run seeds caps from disk
    st2 = StatsStore(capacity=8, path=path)
    assert st2.observed_caps("cpu", plan.fingerprint) == dict(res.caps)
    with scoped_store(st2):
        warm = PlanExecutor(mode="capped").execute(plan, dict(inputs))
    assert warm.attempts == 1
    assert warm.compact().to_pydict() == res.compact().to_pydict()


def test_persistence_knob_off_writes_nothing(tmp_path, monkeypatch):
    # no SPARK_RAPIDS_TPU_STATS_PATH: the store stays in-memory-only
    monkeypatch.delenv("SPARK_RAPIDS_TPU_STATS_PATH", raising=False)
    st = StatsStore(capacity=8, path="")
    assert st.path is None
    with scoped_store(st):
        PlanExecutor(mode="eager").execute(_fanout_plan(),
                                           _fanout_tables())
    assert list(tmp_path.iterdir()) == []


def test_default_store_reads_knobs(tmp_path, monkeypatch):
    path = str(tmp_path / "default.jsonl")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_STATS", "on")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_STATS_PATH", path)
    monkeypatch.setenv("SPARK_RAPIDS_TPU_STATS_CAPACITY", "7")
    stats_mod.reset_default_store()
    try:
        store = stats_mod.active_store()
        assert store is stats_mod.default_store()
        assert store.capacity == 7 and store.path == path
        PlanExecutor(mode="eager").execute(_fanout_plan(),
                                           _fanout_tables())
        assert open(path).read().strip()
        monkeypatch.setenv("SPARK_RAPIDS_TPU_STATS", "off")
        assert stats_mod.active_store() is None
        with pytest.raises(ValueError):
            monkeypatch.setenv("SPARK_RAPIDS_TPU_STATS", "maybe")
            stats_mod.active_store()       # strict-typo policy
    finally:
        stats_mod.reset_default_store()


# ---- stale-stats safety -----------------------------------------------------

def test_schema_changed_fingerprint_never_matches():
    def make(colname):
        b = PlanBuilder()
        return (b.scan("s", schema=["a", colname])
                 .filter(col("a") > 3)
                 .aggregate(["a"], [(colname, "sum", "t")])
                 .build())

    plan_a, plan_b = make("b"), make("c")
    assert plan_a.fingerprint != plan_b.fingerprint
    sub_a = subtree_fingerprints(plan_a.root)
    sub_b = subtree_fingerprints(plan_b.root)
    assert set(sub_a.values()).isdisjoint(sub_b.values()), \
        "a schema change must invalidate every enclosing subtree"
    # executor-level: stats recorded for A are invisible to B
    t_a = Table([_col(np.arange(60) % 9), _col(np.arange(60))],
                names=["a", "b"])
    t_b = Table([_col(np.arange(60) % 9), _col(np.arange(60))],
                names=["a", "c"])
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        PlanExecutor(mode="eager").execute(plan_a, {"s": t_a})
        assert store.generation == 1
        for fp in sub_b.values():
            assert store.observed_rows("cpu", fp) is None
        res = PlanExecutor(mode="eager").execute(plan_b, {"s": t_b})
    assert "observed" not in "".join(
        res.optimizer["decision_sources"].values())


def test_est_rows_hint_change_still_matches():
    """`est_rows` is a pure hint (fingerprint-excluded): re-authoring the
    same plan with different hints must still hit the recorded stats —
    that is exactly the hints-are-wrong case the store corrects."""
    def make(est):
        b = PlanBuilder()
        return (b.scan("s", schema=["a", "b"], est_rows=est)
                 .filter(col("a") > 3)
                 .aggregate(["a"], [("b", "sum", "t")])
                 .build())

    sub1 = subtree_fingerprints(make(10).root)
    sub2 = subtree_fingerprints(make(999_999).root)
    assert sorted(sub1.values()) == sorted(sub2.values())


# ---- backend isolation ------------------------------------------------------

def test_store_is_backend_keyed():
    from spark_rapids_tpu.ops.registry import Signature
    store = StatsStore(capacity=8, path="")
    sig = Signature.of()
    store.record_kernel("cpu", "topk", sig, "pallas", 9.0)
    store.record_kernel("cpu", "topk", sig, "xla", 1.0)
    # cpu-recorded timings never demote on the device backend
    assert store.kernel_slower("tpu", "topk", sig, "pallas", "xla") is None
    assert store.kernel_slower("cpu", "topk", sig, "pallas", "xla") \
        is not None


def test_degraded_run_records_under_cpu_only(tmp_path):
    """Regression (ISSUE 11 satellite): a forced degraded run — the plan
    finishes on the CPU salvage tier after a fatal injected fault — must
    record its stats under backend="cpu", and those entries must never
    seed device-side caps or feed device kernel tie-breaks; the healthy
    run that follows behaves normally."""
    b = PlanBuilder()
    plan = (b.scan("l", schema=["k", "v"])
             .join(b.scan("r", schema=["rk"]), left_on="k", right_on="rk")
             .aggregate(["k"], [("v", "sum", "t")])
             .sort(["k"])
             .build())
    inputs = _fanout_tables()
    cfg = tmp_path / "faultinj.json"
    cfg.write_text(json.dumps({"computeFaults": {
        "plan.Sort": {"percent": 100, "injectionType": 0,
                      "interceptionCount": 1}}}))
    store = StatsStore(capacity=8, path="")
    try:
        faultinj.install(str(cfg))
        with scoped_store(store):
            res = PlanExecutor(mode="eager").execute(plan, dict(inputs))
        assert res.degraded
    finally:
        faultinj.uninstall()
    # everything the degraded run recorded filed under "cpu"
    assert store.generation == 1
    assert all(k[0] == "cpu" for k in store._plans)
    assert all(k[0] == "cpu" for k in store._subtrees)
    assert all(k[0] == "cpu" for k in store._kernels)
    # device-side consults see nothing from the salvage run
    assert store.observed_caps("tpu", plan.fingerprint) == {}
    sub = subtree_fingerprints(plan.root)
    assert all(store.observed_rows("tpu", fp) is None
               for fp in sub.values())
    # degraded results never contribute caps, even under "cpu" (they
    # describe failed device attempts, not a completed sizing)
    assert store.observed_caps("cpu", plan.fingerprint) == {}
    # a healthy run afterwards records and self-tunes normally
    with scoped_store(store):
        healthy = PlanExecutor(mode="eager").execute(plan, dict(inputs))
    assert not healthy.degraded and store.generation == 2


# ---- rendering --------------------------------------------------------------

def test_decision_sources_render_in_profile_and_explain():
    plan = _skew_plan()
    inputs = _skew_tables()
    store = StatsStore(capacity=8, path="")
    with scoped_store(store):
        PlanExecutor(mode="eager").execute(plan, dict(inputs))
        ex = PlanExecutor(mode="eager")
        warm = ex.execute(plan, dict(inputs))
        text = warm.profile_text()
        assert "decision" in text and "(observed:" in text
        shown = ex.explain(plan, optimized=True, inputs=dict(inputs))
        assert "decision sources" in shown and "(observed:" in shown
