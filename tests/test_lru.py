"""LruDict (utils/lru.py): the one bounded-cache definition shared by the
plan executor's program/caps memos and the optimizer's rewrite caches."""
from spark_rapids_tpu.utils import LruDict


def test_insert_evicts_oldest_in_order():
    d = LruDict(maxsize=3)
    for k in "abcd":
        d[k] = k.upper()
    assert list(d) == ["b", "c", "d"]          # "a" was the oldest
    d["e"] = "E"
    assert list(d) == ["c", "d", "e"]


def test_get_refreshes_recency():
    d = LruDict(maxsize=3)
    for k in "abc":
        d[k] = k.upper()
    assert d.get("a") == "A"                   # refresh: "a" now newest
    d["d"] = "D"
    assert "a" in d and "b" not in d           # "b" evicted instead
    assert list(d) == ["c", "a", "d"]


def test_get_miss_returns_default_without_insert():
    d = LruDict(maxsize=2)
    d["a"] = 1
    assert d.get("zz") is None
    assert d.get("zz", 7) == 7
    assert list(d) == ["a"]


def test_overwrite_refreshes_and_keeps_size():
    d = LruDict(maxsize=2)
    d["a"] = 1
    d["b"] = 2
    d["a"] = 10                                # overwrite = most recent
    d["c"] = 3
    assert list(d) == ["a", "c"] and d["a"] == 10


def test_plain_getitem_does_not_refresh():
    d = LruDict(maxsize=2)
    d["a"] = 1
    d["b"] = 2
    assert d["a"] == 1                         # dict semantics: no refresh
    d["c"] = 3
    assert "a" not in d                        # "a" was still the oldest
