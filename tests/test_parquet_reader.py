"""Chunked parquet reader tests. Oracle: pyarrow writes the files AND
provides the expected decoded values (the reference's parquet tests likewise
write with parquet-avro/hadoop and compare — SURVEY.md §4 tier 2)."""
import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.io import ParquetChunkedReader, read_parquet


def _write(tmp_path, table: pa.Table, name="t.parquet", **kw):
    path = str(tmp_path / name)
    pq.write_table(table, path, **kw)
    return path


def _ref_lists(table: pa.Table):
    return {name: table.column(name).to_pylist()
            for name in table.column_names}


def _check(path, ref: dict, columns=None):
    got = read_parquet(path, columns=columns)
    names = columns if columns is not None else list(ref)
    assert list(got.names) == list(names)
    for n in names:
        mine = got[n].to_pylist()
        theirs = ref[n]
        if got[n].dtype.is_floating:
            assert len(mine) == len(theirs)
            for a, b in zip(mine, theirs):
                assert (a is None) == (b is None)
                if a is not None:
                    assert a == pytest.approx(b, rel=1e-6)
        else:
            assert mine == theirs, f"column {n}"


def test_plain_types_roundtrip(tmp_path):
    n = 1000
    rng = np.random.default_rng(0)
    t = pa.table({
        "i32": pa.array(rng.integers(-2**31, 2**31 - 1, n), pa.int32()),
        "i64": pa.array(rng.integers(-2**62, 2**62, n), pa.int64()),
        "f32": pa.array(rng.standard_normal(n), pa.float32()),
        "f64": pa.array(rng.standard_normal(n), pa.float64()),
        "b": pa.array(rng.integers(0, 2, n) == 1, pa.bool_()),
    })
    path = _write(tmp_path, t, use_dictionary=False, compression="NONE")
    _check(path, _ref_lists(t))


def test_strings_with_nulls_and_dictionary(tmp_path):
    vals = ["alpha", None, "", "beta", "alpha", None, "γunicodeγ", "beta"] * 50
    t = pa.table({"s": pa.array(vals, pa.string())})
    for comp, dict_on in (("NONE", True), ("SNAPPY", True), ("ZSTD", False)):
        path = _write(tmp_path, t, use_dictionary=dict_on, compression=comp,
                      name=f"s_{comp}.parquet")
        _check(path, _ref_lists(t))


def test_codecs(tmp_path):
    n = 5000
    rng = np.random.default_rng(1)
    # low-cardinality ints → dictionary pages; plus nulls
    raw = rng.integers(0, 50, n).astype(np.int64)
    mask = rng.random(n) < 0.1
    vals = [None if m else int(v) for v, m in zip(raw, mask)]
    t = pa.table({"x": pa.array(vals, pa.int64())})
    for comp in ("NONE", "SNAPPY", "GZIP", "ZSTD"):
        path = _write(tmp_path, t, compression=comp, name=f"c_{comp}.parquet")
        _check(path, _ref_lists(t))


def test_multiple_row_groups_chunked(tmp_path):
    n = 10_000
    t = pa.table({"x": pa.array(np.arange(n), pa.int64())})
    path = _write(tmp_path, t, row_group_size=1024)
    with ParquetChunkedReader(path) as r:
        assert r.num_row_groups == (n + 1023) // 1024
        total = []
        n_chunks = 0
        while r.has_next():
            chunk = r.read_chunk()
            assert chunk.num_rows <= 1024
            total.extend(chunk["x"].to_pylist())
            n_chunks += 1
        assert n_chunks == r.num_row_groups
        assert total == list(range(n))


def test_column_projection(tmp_path):
    t = pa.table({"a": pa.array([1, 2, 3], pa.int32()),
                  "b": pa.array(["x", "y", "z"]),
                  "c": pa.array([1.5, 2.5, 3.5], pa.float64())})
    path = _write(tmp_path, t)
    _check(path, _ref_lists(t), columns=["c", "a"])
    with pytest.raises(KeyError):
        read_parquet(path, columns=["nope"])


def test_date_and_timestamps(tmp_path):
    import datetime
    days = [datetime.date(2020, 1, 1), None, datetime.date(1969, 12, 31)]
    us = [datetime.datetime(2023, 5, 17, 1, 2, 3, 123456), None,
          datetime.datetime(1960, 1, 1)]
    t = pa.table({"d": pa.array(days, pa.date32()),
                  "ts": pa.array(us, pa.timestamp("us"))})
    path = _write(tmp_path, t)
    got = read_parquet(path)
    assert got["d"].dtype == spark_rapids_tpu.dtypes.DATE32
    assert got["d"].to_pylist() == [18262, None, -1]
    assert got["ts"].dtype == spark_rapids_tpu.dtypes.TIMESTAMP_US
    epoch = datetime.datetime(1970, 1, 1)
    ref = [None if x is None else
           int((x - epoch) // datetime.timedelta(microseconds=1)) for x in us]
    assert got["ts"].to_pylist() == ref


def test_int96_legacy_timestamps(tmp_path):
    import datetime
    us = [datetime.datetime(2001, 2, 3, 4, 5, 6, 789000), None,
          datetime.datetime(1970, 1, 1)]
    t = pa.table({"ts": pa.array(us, pa.timestamp("us"))})
    path = str(tmp_path / "i96.parquet")
    pq.write_table(t, path, use_deprecated_int96_timestamps=True)
    got = read_parquet(path)
    assert got["ts"].dtype == spark_rapids_tpu.dtypes.TIMESTAMP_US
    epoch = datetime.datetime(1970, 1, 1)
    ref = [None if x is None else
           int((x - epoch) // datetime.timedelta(microseconds=1)) for x in us]
    assert got["ts"].to_pylist() == ref


def test_decimal128_flba(tmp_path):
    vals = [decimal.Decimal("123456789012345678901234.567"), None,
            decimal.Decimal("-0.001"), decimal.Decimal("99.999")]
    t = pa.table({"dec": pa.array(vals, pa.decimal128(38, 3))})
    path = _write(tmp_path, t)
    got = read_parquet(path)
    assert got["dec"].dtype.kind == spark_rapids_tpu.dtypes.Kind.DECIMAL128
    assert got["dec"].dtype.scale == 3
    unscaled = [None if v is None else int(v.scaleb(3)) for v in vals]
    assert got["dec"].to_pylist() == unscaled


def test_decimal64_int_backed(tmp_path):
    vals = [decimal.Decimal("12.34"), decimal.Decimal("-5.00"), None]
    t = pa.table({"d": pa.array(vals, pa.decimal128(10, 2))})
    # force int64 storage for small precision
    path = str(tmp_path / "d64.parquet")
    pq.write_table(t, path, store_decimal_as_integer=True)
    got = read_parquet(path)
    assert got["d"].dtype.kind in (spark_rapids_tpu.dtypes.Kind.DECIMAL32,
                                   spark_rapids_tpu.dtypes.Kind.DECIMAL64)
    assert got["d"].to_pylist() == [1234, -500, None]


def test_data_page_v2(tmp_path):
    vals = [None if i % 7 == 0 else i * 11 for i in range(3000)]
    t = pa.table({"x": pa.array(vals, pa.int64())})
    path = _write(tmp_path, t, data_page_version="2.0", compression="SNAPPY")
    _check(path, _ref_lists(t))


def test_all_nulls_column(tmp_path):
    t = pa.table({"x": pa.array([None, None, None], pa.int32())})
    path = _write(tmp_path, t)
    assert read_parquet(path)["x"].to_pylist() == [None, None, None]


def test_empty_file(tmp_path):
    t = pa.table({"x": pa.array([], pa.int64()),
                  "s": pa.array([], pa.string())})
    path = _write(tmp_path, t)
    got = read_parquet(path)
    assert got.num_rows == 0 and got["x"].to_pylist() == []


def test_random_mixed_against_pyarrow(tmp_path):
    rng = np.random.default_rng(7)
    n = 20_000
    mask = rng.random(n) < 0.15
    ints = [None if m else int(v) for m, v in
            zip(mask, rng.integers(-10**12, 10**12, n))]
    strs = [None if rng.random() < 0.1 else
            "".join(chr(97 + int(c)) for c in rng.integers(0, 26, rng.integers(0, 12)))
            for _ in range(n)]
    t = pa.table({"i": pa.array(ints, pa.int64()),
                  "s": pa.array(strs, pa.string())})
    path = _write(tmp_path, t, row_group_size=4096, compression="SNAPPY")
    _check(path, _ref_lists(t))


def test_delta_encodings(tmp_path):
    """DELTA_BINARY_PACKED / DELTA_BYTE_ARRAY / DELTA_LENGTH_BYTE_ARRAY —
    what parquet-mr v2 pages emit (e.g. Spark with parquet.writer.version=v2)."""
    rng = np.random.default_rng(0)
    n = 5000
    t = pa.table({
        "i32": pa.array(rng.integers(-10**6, 10**6, n).astype(np.int32)),
        "i64": pa.array(np.cumsum(rng.integers(-1000, 1000, n)).astype(np.int64)),
        "s": pa.array([None if i % 11 == 0 else f"prefix-{i//3}-suffix{i}"
                       for i in range(n)]),
    })
    for scol_enc, comp in (("DELTA_BYTE_ARRAY", "NONE"),
                           ("DELTA_LENGTH_BYTE_ARRAY", "SNAPPY")):
        path = str(tmp_path / f"delta_{scol_enc}.parquet")
        pq.write_table(t, path, use_dictionary=False, data_page_version="2.0",
                       column_encoding={"i32": "DELTA_BINARY_PACKED",
                                        "i64": "DELTA_BINARY_PACKED",
                                        "s": scol_enc},
                       compression=comp, row_group_size=1234)
        got = read_parquet(path)
        for name in ("i32", "i64", "s"):
            assert got[name].to_pylist() == t.column(name).to_pylist(), name


def test_byte_stream_split(tmp_path):
    rng = np.random.default_rng(1)
    n = 3000
    t = pa.table({
        "f": pa.array(rng.standard_normal(n).astype(np.float32)),
        "d": pa.array(rng.standard_normal(n)),
    })
    path = str(tmp_path / "bss.parquet")
    pq.write_table(t, path, use_dictionary=False, use_byte_stream_split=True,
                   compression="ZSTD", row_group_size=777)
    got = read_parquet(path)
    np.testing.assert_array_equal(np.asarray(got["f"].data),
                                  t.column("f").to_numpy())
    np.testing.assert_array_equal(np.asarray(got["d"].data),
                                  t.column("d").to_numpy())


class TestListColumns:
    """Standard 3-level LIST<primitive> decoding (Spark array columns):
    null list vs empty list vs null element, across page versions, codecs,
    dictionary and delta encodings."""

    ROWS_I = [[1, 2, 3], None, [], [4, None, 6], [7]] * 400
    ROWS_S = [["a", "bb"], [], None, [None, "ccc"], ["d"]] * 400

    def _table(self):
        return pa.table({
            "li": pa.array(self.ROWS_I, pa.list_(pa.int64())),
            "ls": pa.array(self.ROWS_S, pa.list_(pa.utf8())),
            "flat": pa.array(range(len(self.ROWS_I))),
        })

    @pytest.mark.parametrize("kw", [
        dict(version="1.0", compression="SNAPPY"),
        dict(version="2.6", compression="ZSTD"),
        dict(data_page_version="2.0"),
        dict(use_dictionary=False, data_page_version="2.0",
             column_encoding={"li": "DELTA_BINARY_PACKED",
                              "ls": "DELTA_BYTE_ARRAY",
                              "flat": "DELTA_BINARY_PACKED"}),
    ])
    def test_round_trip(self, tmp_path, kw):
        path = str(tmp_path / "lists.parquet")
        pq.write_table(self._table(), path, row_group_size=777, **kw)
        got = read_parquet(path)
        assert list(got.names) == ["li", "ls", "flat"]
        assert got["li"].to_pylist() == self.ROWS_I
        assert got["ls"].to_pylist() == self.ROWS_S
        assert got["flat"].to_pylist() == list(range(len(self.ROWS_I)))

    def test_column_selection_by_outer_name(self, tmp_path):
        path = str(tmp_path / "sel.parquet")
        pq.write_table(self._table(), path)
        got = read_parquet(path, columns=["ls"])
        assert list(got.names) == ["ls"]
        assert got["ls"].to_pylist() == self.ROWS_S

    def test_required_elements(self, tmp_path):
        t = pa.table({"l": pa.array([[1], [2, 3], []],
                                    pa.list_(pa.field("item", pa.int32(),
                                                      nullable=False)))})
        path = str(tmp_path / "req.parquet")
        pq.write_table(t, path)
        got = read_parquet(path)
        assert got["l"].to_pylist() == [[1], [2, 3], []]


def test_map_and_nested_struct_shapes_decode(tmp_path):
    """MAP, LIST<STRUCT> and STRUCT<LIST> decode through the generalized
    Dremel path (round-2: these were skip-listed in round 1). Maps surface
    as LIST<STRUCT<key,value>> — the engine's map representation
    (ops/map_utils.py produces the same shape)."""
    t = pa.table({
        "m": pa.array([{"a": 1}, {"b": 2}], pa.map_(pa.utf8(), pa.int64())),
        "lstruct": pa.array([[{"x": 1}], []],
                            pa.list_(pa.struct([("x", pa.int64())]))),
        "slist": pa.array([{"v": [1, 2]}, {"v": []}],
                          pa.struct([("v", pa.list_(pa.int64()))])),
        "ok": pa.array([10, 20]),
        "larr": pa.array([[1, 2], [3]], pa.list_(pa.int64())),
    })
    path = str(tmp_path / "mixed.parquet")
    pq.write_table(t, path)
    got = read_parquet(path)
    assert list(got.names) == ["m", "lstruct", "slist", "ok", "larr"]
    assert got["m"].to_pylist() == [[{"key": "a", "value": 1}],
                                    [{"key": "b", "value": 2}]]
    assert got["lstruct"].to_pylist() == [[{"x": 1}], []]
    assert got["slist"].to_pylist() == [{"v": [1, 2]}, {"v": []}]
    assert got["ok"].to_pylist() == [10, 20]
    assert got["larr"].to_pylist() == [[1, 2], [3]]


class TestStructColumns:
    """STRUCT<primitive> members decode flat + raw def levels; ancestor
    validity is rebuilt from the def threshold at each optional group."""

    ROWS = [{"x": 1, "y": "a"}, None, {"x": None, "y": "c"},
            {"x": 4, "y": None}] * 300
    DEEP = [{"inner": {"p": 1.5}, "q": 7}, {"inner": None, "q": 8}, None,
            {"inner": {"p": None}, "q": None}] * 300

    def _table(self):
        return pa.table({
            "s": pa.array(self.ROWS, pa.struct([("x", pa.int64()),
                                                ("y", pa.utf8())])),
            "d": pa.array(self.DEEP,
                          pa.struct([("inner",
                                      pa.struct([("p", pa.float64())])),
                                     ("q", pa.int32())])),
            "flat": pa.array(range(len(self.ROWS))),
        })

    @pytest.mark.parametrize("kw", [
        dict(),
        dict(data_page_version="2.0", compression="ZSTD"),
    ])
    def test_round_trip_multi_row_group(self, tmp_path, kw):
        path = str(tmp_path / "structs.parquet")
        pq.write_table(self._table(), path, row_group_size=500, **kw)
        got = read_parquet(path)
        assert list(got.names) == ["s", "d", "flat"]
        assert got["s"].to_pylist() == self.ROWS
        assert got["d"].to_pylist() == self.DEEP
        assert got["flat"].to_pylist() == list(range(len(self.ROWS)))

    def test_column_selection(self, tmp_path):
        path = str(tmp_path / "sel.parquet")
        pq.write_table(self._table(), path)
        got = read_parquet(path, columns=["d", "flat"])
        assert list(got.names) == ["d", "flat"]
        assert got["d"].to_pylist() == self.DEEP

    def test_required_struct_fields(self, tmp_path):
        t = pa.table({"s": pa.array(
            [{"a": 1}, {"a": 2}],
            pa.struct([pa.field("a", pa.int64(), nullable=False)]))})
        path = str(tmp_path / "req.parquet")
        pq.write_table(t, path)
        got = read_parquet(path)
        assert got["s"].to_pylist() == [{"a": 1}, {"a": 2}]


def test_optional_struct_all_required_members(tmp_path):
    """max_def==1: an optional struct whose members are all required — the
    null struct row must not surface as a fabricated zero row."""
    t = pa.table({"s": pa.array(
        [{"a": 1}, None, {"a": 3}],
        pa.struct([pa.field("a", pa.int64(), nullable=False)]))})
    path = str(tmp_path / "opt_req.parquet")
    pq.write_table(t, path)
    got = read_parquet(path)
    assert got["s"].to_pylist() == [{"a": 1}, None, {"a": 3}]
    # the child column itself must carry the ancestor-null rows as nulls
    assert got["s"].children[0].to_pylist() == [1, None, 3]


def test_struct_with_mixed_members_decodes_whole(tmp_path):
    """struct<x:int64, v:list<int64>>: the plain member and the
    list-bearing member assemble through one slot-stream model (round 1
    dropped the whole field)."""
    t = pa.table({
        "s": pa.array([{"x": 1, "v": [1, 2]}],
                      pa.struct([("x", pa.int64()),
                                 ("v", pa.list_(pa.int64()))])),
        "ok": pa.array([5]),
    })
    path = str(tmp_path / "partial.parquet")
    pq.write_table(t, path)
    got = read_parquet(path)
    assert list(got.names) == ["s", "ok"]
    assert got["s"].to_pylist() == [{"x": 1, "v": [1, 2]}]
    assert got["ok"].to_pylist() == [5]


# ---- row-group selection + typed-empty regression (streaming IO) ------------

def test_row_groups_selection(tmp_path):
    """row_groups= restricts the chunk sequence to the given groups, in
    the given order, composing with columns= selective decode."""
    n = 4000
    t = pa.table({"a": pa.array(range(n), pa.int64()),
                  "b": pa.array([i * 0.5 for i in range(n)], pa.float64())})
    path = _write(tmp_path, t, row_group_size=1000, compression="NONE")
    got = read_parquet(path, columns=["a"], row_groups=[1, 3])
    assert list(got.names) == ["a"]
    assert got["a"].to_pylist() == list(range(1000, 2000)) + \
        list(range(3000, 4000))
    with ParquetChunkedReader(path, row_groups=[2]) as r:
        assert r.num_row_groups == 4          # file total, not selection
        assert r.has_next()
        chunk = r.read_chunk()
        assert chunk["a"].to_pylist() == list(range(2000, 3000))
        assert not r.has_next()
    with pytest.raises(IndexError):
        ParquetChunkedReader(path, row_groups=[4])


def test_read_all_zero_row_groups_typed_empty(tmp_path):
    """read_all() over an empty selection returns the TYPED empty table —
    the _empty_columns path — including under columns= selection."""
    n = 100
    t = pa.table({"a": pa.array(range(n), pa.int64()),
                  "s": pa.array([f"v{i}" for i in range(n)], pa.string()),
                  "f": pa.array([i * 1.5 for i in range(n)], pa.float64())})
    path = _write(tmp_path, t, compression="NONE")
    from spark_rapids_tpu import dtypes
    got = read_parquet(path, row_groups=[])
    assert got.num_rows == 0
    assert list(got.names) == ["a", "s", "f"]
    assert got["a"].dtype == dtypes.INT64
    assert got["s"].dtype == dtypes.STRING
    assert got["f"].dtype == dtypes.FLOAT64
    # with columns= selection: the typed empty respects the selection
    got = read_parquet(path, columns=["f", "a"], row_groups=[])
    assert got.num_rows == 0
    assert list(got.names) == ["f", "a"]
    assert got["f"].dtype == dtypes.FLOAT64
    assert got["a"].dtype == dtypes.INT64


def test_read_all_zero_row_group_file():
    """A parquet file with ZERO row groups (pyarrow: empty table) decodes
    to the typed empty table, with and without columns=."""
    import io as _io
    from spark_rapids_tpu import dtypes
    t = pa.table({"a": pa.array([], pa.int64()),
                  "s": pa.array([], pa.string())})
    sink = _io.BytesIO()
    pq.write_table(t, sink, compression="NONE")
    data = sink.getvalue()
    md = pq.read_metadata(_io.BytesIO(data))
    with ParquetChunkedReader(data) as r:
        assert r.num_row_groups == md.num_row_groups
        got = r.read_all()
    assert got.num_rows == 0
    assert list(got.names) == ["a", "s"]
    assert got["a"].dtype == dtypes.INT64
    got = read_parquet(data, columns=["s"])
    assert got.num_rows == 0
    assert list(got.names) == ["s"]
    assert got["s"].dtype == dtypes.STRING
