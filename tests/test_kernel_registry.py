"""Kernel registry (ops/registry.py, docs/kernels.md): selection mechanics,
the Pallas kernel parity matrix, and executor integration.

The parity suite runs every registered non-fallback kernel FORCED against
its XLA fallback (interpret mode on this CPU suite) across the supported
dtype x validity matrix, plus the decline/edge cases the registry contract
promises: all-dead rows, empty tables, 64-bit (f64-guard class) columns,
and unsupported signatures declining to the fallback WITHOUT erroring."""
import numpy as np
import numpy.testing as npt
import pytest

import jax.numpy as jnp

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import Column, Table, dtypes
from spark_rapids_tpu.ops import (apply_boolean_mask, inner_join,
                                  inner_join_capped, slice_table, sort_table,
                                  sort_table_capped, take_table)
from spark_rapids_tpu.ops import join_pallas, select_pallas, topk_pallas
from spark_rapids_tpu.ops.registry import REGISTRY, Signature
from spark_rapids_tpu.plan import PlanBuilder, PlanExecutor, col, lit


def _assert_tables_equal(a: Table, b: Table):
    assert list(a.names) == list(b.names)
    assert a.num_rows == b.num_rows
    for ca, cb in zip(a.columns, b.columns):
        npt.assert_array_equal(np.asarray(ca.data), np.asarray(cb.data))
        va = None if ca.validity is None else np.asarray(ca.validity)
        vb = None if cb.validity is None else np.asarray(cb.validity)
        if va is None and vb is None:
            continue
        na = np.zeros(a.num_rows, bool) if va is None else ~va
        nb = np.zeros(b.num_rows, bool) if vb is None else ~vb
        npt.assert_array_equal(na, nb)


# ---- registry mechanics -----------------------------------------------------

def test_backend_ranking():
    # cpu backend prefers the cpu-registered kernel; any other backend
    # lands on the universal fallback
    assert REGISTRY.select("groupby", backend="cpu").name == "scatter"
    assert REGISTRY.select("groupby", backend="tpu").name == "scan"
    assert REGISTRY.select("row_conversion", backend="cpu").name == "concat"
    assert REGISTRY.select("row_conversion", backend="tpu").name == "word"
    # conditional kernels need a signature: blind selection declines
    ch = REGISTRY.select("topk", None, backend="tpu")
    assert ch.fallback and ("pallas", "no signature at call site") \
        in ch.declined


def test_override_forcing(monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "groupby=scan")
    assert REGISTRY.select("groupby", backend="cpu").name == "scan"
    # the EXECUTED dispatch follows the registry, not a parallel env read —
    # the regression class where the knob is validated but ignored
    from spark_rapids_tpu.ops.aggregate import _use_scan_kernel
    from spark_rapids_tpu.ops.row_conversion import _use_word_kernel
    assert _use_scan_kernel()
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "row_conversion=word")
    assert _use_word_kernel()
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "row_conversion=concat")
    assert not _use_word_kernel()
    # legacy alias still works, explicit entry wins over it
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_GROUPBY_KERNEL", "scan")
    assert REGISTRY.select("groupby", backend="cpu").name == "scan"
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "groupby=scatter")
    assert REGISTRY.select("groupby", backend="cpu").name == "scatter"


def test_strict_typo_policy(monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "groupby=scna")
    with pytest.raises(ValueError, match="unknown kernel"):
        REGISTRY.select("groupby")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "gruopby=scan")
    with pytest.raises(ValueError, match="unknown kernel op"):
        REGISTRY.select("groupby")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "groupby")
    with pytest.raises(ValueError, match="malformed"):
        REGISTRY.select("groupby")
    with pytest.raises(ValueError, match="unknown kernel op"):
        REGISTRY.select("no_such_op")


def test_forced_override_honors_pinned_backend(monkeypatch):
    # an EXPLICIT backend pin (the degraded tier passes "cpu" so nothing
    # lands on the quarantined device) outranks a forced override; without
    # a pin the force crosses the registration gate (interpret-mode runs)
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "topk=pallas")
    t = Table([Column.from_numpy(np.arange(10, dtype=np.int64))],
              names=["a"])
    sig = topk_pallas.make_signature(t, ["a"], [True], 3, "eager")
    assert REGISTRY.select("topk", sig).name == "pallas"
    pinned = REGISTRY.select("topk", sig, backend="cpu")
    assert pinned.fallback
    assert any("pinned backend" in why for _, why in pinned.declined)


def test_forced_unsupported_signature_declines(monkeypatch):
    # a FORCED kernel whose supports() rejects the signature falls back
    # cleanly — a signature is data, not a typo
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "topk=pallas")
    sig = Signature(columns=(("string", False),),
                    extras=(("limit", 5), ("tier", "eager")))
    ch = REGISTRY.select("topk", sig)
    assert ch.fallback and ch.name == "xla"
    assert ("pallas", "unsupported signature") in ch.declined


def test_summary_is_backend_floor():
    s = REGISTRY.summary(backend="cpu")
    assert s["groupby"] == "scatter"
    assert s["fused_select"] == "xla"     # pallas is tpu-only
    s = REGISTRY.summary(backend="tpu")
    # conditional kernels resolve per dispatch: summary shows the floor
    assert s["fused_select"] == "xla" and s["groupby"] == "scan"


# ---- fused_select parity matrix ---------------------------------------------

_FS_DTYPES = [np.int8, np.int16, np.int32, np.int64, np.float32,
              np.float64, np.bool_]


def _fs_table(n=700, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    cols, names = [], []
    for i, dt in enumerate(_FS_DTYPES):
        if dt is np.bool_:
            arr = rng.integers(0, 2, n).astype(bool)
        elif np.issubdtype(dt, np.floating):
            arr = rng.standard_normal(n).astype(dt)
            arr[rng.random(n) < 0.05] = np.nan
        else:
            info = np.iinfo(dt)
            arr = rng.integers(info.min, info.max, n, dtype=dt,
                               endpoint=True)
        valid = (rng.random(n) > 0.15) if (with_nulls and i % 2) else None
        cols.append(Column.from_numpy(arr, validity=valid))
        names.append(f"c_{np.dtype(dt).name}")
    cols.append(Column.from_numpy(rng.integers(0, 50, n).astype(np.int32)))
    names.append("sel")
    return Table(cols, names=names)


@pytest.mark.parametrize("with_nulls", [False, True])
def test_fused_select_dtype_matrix(with_nulls):
    t = _fs_table(with_nulls=with_nulls)
    pred = (col("sel") < 25) | (col("sel") > 48)
    needed = [n for n in t.names if n != "sel"]
    ref = apply_boolean_mask(t.select(needed), pred.evaluate(t))
    got = select_pallas.fused_select_compact(t, pred, needed,
                                             block_rows=256)
    _assert_tables_equal(ref, got)


def test_fused_select_predicate_shapes():
    t = _fs_table(with_nulls=True)
    preds = [
        col("sel") == 7,
        (col("sel") + 3) * 2 > 40,
        ~(col("sel") >= 10) & (col("c_bool") | (col("sel") != 3)),
        col("sel") - 60 < lit(-30),
    ]
    for pred in preds:
        ref = apply_boolean_mask(t.select(["c_int64"]), pred.evaluate(t))
        got = select_pallas.fused_select_compact(t, pred, ["c_int64"],
                                                 block_rows=256)
        _assert_tables_equal(ref, got)


def test_fused_select_literal_weak_typing_parity():
    # literals stay weak-typed in BOTH paths: i8 arithmetic with an int
    # literal wraps in int8 exactly like the fallback (the column dtype
    # wins promotion), and pure-literal subtrees decline
    rng = np.random.default_rng(12)
    n = 400
    t = Table([Column.from_numpy(
        rng.integers(-128, 127, n, dtype=np.int8, endpoint=True)),
        Column.from_numpy(np.arange(n, dtype=np.int64))],
        names=["b", "v"])
    pred = (col("b") + 100) > 50        # wraps in int8 near the top
    ref = apply_boolean_mask(t.select(["v"]), pred.evaluate(t))
    got = select_pallas.fused_select_compact(t, pred, ["v"],
                                             block_rows=256)
    _assert_tables_equal(ref, got)
    from spark_rapids_tpu.plan.expr import BinOp, Literal
    folded_away = BinOp(">", BinOp("+", Literal(2), Literal(3)),
                        Literal(4))
    sig = select_pallas.make_signature(t, folded_away, (("v", col("v")),),
                                       "eager")
    assert not select_pallas._supports(sig)


def test_fused_select_all_dead_and_empty():
    t = _fs_table()
    got = select_pallas.fused_select_compact(t, col("sel") > 10 ** 6,
                                             ["c_int32"], block_rows=256)
    assert got.num_rows == 0
    t0 = Table([Column.from_numpy(np.zeros(0, np.int32))], names=["a"])
    got = select_pallas.fused_select_compact(t0, col("a") > 0, ["a"],
                                             block_rows=256)
    assert got.num_rows == 0 and got["a"].dtype == dtypes.INT32


def test_fused_select_signature_declines():
    t = _fs_table()
    exprs = (("x", col("c_int32")),)
    # float / 64-bit predicate inputs: the f64-guard class
    for pred in (col("c_float64") > 0.0, col("c_int64") > 0):
        sig = select_pallas.make_signature(t, pred, exprs, "eager")
        assert not select_pallas._supports(sig)
        assert REGISTRY.select("fused_select", sig,
                               backend="tpu").fallback
    # capped tier has no compaction to fuse
    sig = select_pallas.make_signature(t, col("sel") > 0, exprs, "capped")
    assert not select_pallas._supports(sig)
    # scalar-aggregate predicates are not row-wise
    from spark_rapids_tpu.plan import scalar_max
    sig = select_pallas.make_signature(
        t, col("sel") > scalar_max(col("sel")), exprs, "eager")
    assert not select_pallas._supports(sig)
    # string projection declines (unsupported plane dtype)
    st = Table([Column.from_pylist([b"a", b"bb", b"ccc"], dtypes.STRING),
                Column.from_numpy(np.arange(3, dtype=np.int32))],
               names=["s", "k"])
    sig = select_pallas.make_signature(st, col("k") > 0, (("s", col("s")),),
                                       "eager")
    assert not select_pallas._supports(sig)


# ---- topk parity matrix -----------------------------------------------------

_TK_CASES = [
    (np.int64, True), (np.int64, False),
    (np.int32, True), (np.int16, False),
    (np.float32, True), (np.float64, False),
    (np.bool_, True),
]


@pytest.mark.parametrize("dt,asc", _TK_CASES)
@pytest.mark.parametrize("with_nulls", [False, True])
def test_topk_dtype_matrix(dt, asc, with_nulls):
    rng = np.random.default_rng(3)
    n, k = 900, 17
    if dt is np.bool_:
        arr = rng.integers(0, 2, n).astype(bool)
    elif np.issubdtype(dt, np.floating):
        arr = rng.standard_normal(n).astype(dt)
        arr[rng.random(n) < 0.05] = np.nan
    else:
        arr = rng.integers(np.iinfo(dt).min, np.iinfo(dt).max, n,
                           dtype=dt, endpoint=True)
    valid = (rng.random(n) > 0.2) if with_nulls else None
    t = Table([Column.from_numpy(arr, validity=valid),
               Column.from_numpy(rng.integers(0, 9, n).astype(np.int32))],
              names=["k", "pay"])
    ref = slice_table(sort_table(t, key_names=["k"], ascending=[asc]), 0, k)
    got = topk_pallas.topk_table(t, ["k"], [asc], k, block_rows=256)
    _assert_tables_equal(ref, got)


def test_topk_multikey_and_edges():
    rng = np.random.default_rng(4)
    n = 500
    t = Table([Column.from_numpy(rng.integers(0, 4, n).astype(np.int64),
                                 validity=rng.random(n) > 0.1),
               Column.from_numpy(rng.standard_normal(n).astype(np.float64))],
              names=["a", "b"])
    for asc in ([True, False], [False, True]):
        ref = slice_table(sort_table(t, key_names=["a", "b"],
                                     ascending=asc), 0, 11)
        got = topk_pallas.topk_table(t, ["a", "b"], asc, 11, block_rows=256)
        _assert_tables_equal(ref, got)
    # k > n clamps to the relation
    ref = sort_table(t, key_names=["a"], ascending=[True])
    got = topk_pallas.topk_table(t, ["a"], [True], n + 50, block_rows=256)
    _assert_tables_equal(ref, got)
    # empty table
    t0 = Table([Column.from_numpy(np.zeros(0, np.int64))], names=["a"])
    assert topk_pallas.topk_table(t0, ["a"], [True], 5).num_rows == 0


def test_topk_capped_alive_and_all_dead():
    rng = np.random.default_rng(5)
    n, k = 800, 9
    t = Table([Column.from_numpy(rng.integers(-99, 99, n).astype(np.int64)),
               Column.from_numpy(rng.integers(0, 7, n).astype(np.int32))],
              names=["k", "pay"])
    for alive_p in (0.6, 0.0):
        alive = jnp.asarray(rng.random(n) < alive_p)
        st, salive = sort_table_capped(t, key_names=["k"],
                                       ascending=[False], alive=alive)
        prefix = jnp.cumsum(salive.astype(jnp.int32))
        ref_alive = salive & (prefix <= k)
        ridx = jnp.asarray(np.nonzero(np.asarray(ref_alive))[0],
                           dtype=jnp.int32)
        ref = take_table(st, ridx, _has_negative=False)
        gt, ga = topk_pallas.topk_capped(t, ["k"], [False], k, alive,
                                         block_rows=256)
        gidx = jnp.asarray(np.nonzero(np.asarray(ga))[0], dtype=jnp.int32)
        _assert_tables_equal(ref, take_table(gt, gidx, _has_negative=False))


def test_topk_signature_declines():
    t = Table([Column.from_pylist([b"a", b"b"], dtypes.STRING)],
              names=["s"])
    sig = topk_pallas.make_signature(t, ["s"], [True], 5, "eager")
    assert not topk_pallas._supports(sig)
    t2 = Table([Column.from_numpy(np.arange(5, dtype=np.int64))],
               names=["a"])
    big = topk_pallas.make_signature(t2, ["a"], [True],
                                     topk_pallas.MAX_K + 1, "eager")
    assert not topk_pallas._supports(big)
    ok = topk_pallas.make_signature(t2, ["a"], [True], 5, "capped")
    assert topk_pallas._supports(ok)


# ---- hash_join parity matrix ------------------------------------------------

_HJ_DTYPES = [np.int64, np.int32, np.int16, np.bool_]


@pytest.mark.parametrize("dt", _HJ_DTYPES)
@pytest.mark.parametrize("with_nulls", [False, True])
def test_hash_join_dtype_matrix(dt, with_nulls):
    rng = np.random.default_rng(6)
    nl, nr = 1200, 250
    if dt is np.bool_:
        lk, rk = (rng.integers(0, 2, nl).astype(bool),
                  rng.integers(0, 2, nr).astype(bool))
    else:
        lk = rng.integers(0, 150, nl).astype(dt)
        rk = rng.integers(0, 150, nr).astype(dt)
    lv = (rng.random(nl) > 0.1) if with_nulls else None
    rv = (rng.random(nr) > 0.1) if with_nulls else None
    lc = [Column.from_numpy(lk, validity=lv)]
    rc = [Column.from_numpy(rk, validity=rv)]
    rl, rr = inner_join(lc, rc)
    gl, gr = join_pallas.inner_join_pallas(lc, rc)
    npt.assert_array_equal(np.asarray(rl.data), np.asarray(gl.data))
    npt.assert_array_equal(np.asarray(rr.data), np.asarray(gr.data))


def test_hash_join_multikey_and_capped():
    rng = np.random.default_rng(7)
    nl, nr = 900, 180
    lc = [Column.from_numpy(rng.integers(0, 40, nl).astype(np.int64)),
          Column.from_numpy(rng.integers(0, 3, nl).astype(np.int32),
                            validity=rng.random(nl) > 0.05)]
    rc = [Column.from_numpy(rng.integers(0, 40, nr).astype(np.int64)),
          Column.from_numpy(rng.integers(0, 3, nr).astype(np.int32))]
    rl, rr = inner_join(lc, rc)
    gl, gr = join_pallas.inner_join_pallas(lc, rc)
    npt.assert_array_equal(np.asarray(rl.data), np.asarray(gl.data))
    npt.assert_array_equal(np.asarray(rr.data), np.asarray(gr.data))
    lalive = jnp.asarray(rng.random(nl) > 0.4)
    ralive = jnp.asarray(rng.random(nr) > 0.4)
    for cap in (8192, 13):                  # roomy + overflowing
        ref = inner_join_capped(lc, rc, row_cap=cap, lalive=lalive,
                                ralive=ralive)
        got = join_pallas.inner_join_capped_pallas(
            lc, rc, row_cap=cap, lalive=lalive, ralive=ralive)
        for i, (a, b) in enumerate(zip(ref, got)):
            npt.assert_array_equal(np.asarray(a), np.asarray(b),
                                   err_msg=f"cap={cap} part {i}")


def test_hash_join_all_null_and_empty():
    rng = np.random.default_rng(8)
    lc = [Column.from_numpy(rng.integers(0, 5, 300).astype(np.int64),
                            validity=np.zeros(300, bool))]
    rc = [Column.from_numpy(rng.integers(0, 5, 50).astype(np.int64))]
    gl, gr = join_pallas.inner_join_pallas(lc, rc)
    assert gl.length == 0                    # null keys never match
    e = [Column.from_numpy(np.zeros(0, np.int64))]
    gl, gr = join_pallas.inner_join_pallas(e, e)
    assert gl.length == 0


def test_hash_join_signature_declines():
    f = [Column.from_numpy(np.zeros(4, np.float32))]
    i = [Column.from_numpy(np.zeros(4, np.int64))]
    assert not join_pallas._supports(
        join_pallas.make_signature(f, f, "inner", "eager"))
    assert not join_pallas._supports(
        join_pallas.make_signature(i, i, "left_semi", "eager"))
    big = [Column.from_numpy(np.zeros(join_pallas.MAX_BUILD + 1, np.int64))]
    assert not join_pallas._supports(
        join_pallas.make_signature(i, big, "inner", "eager"))
    assert join_pallas._supports(
        join_pallas.make_signature(big, i, "inner", "capped"))


# ---- executor integration ---------------------------------------------------

def _mini_plan():
    b = PlanBuilder()
    facts = b.scan("facts", schema=["k", "v"])
    dims = b.scan("dims", schema=["dk", "tag"]).filter(col("tag") > 2)
    j = facts.join(dims, left_on="k", right_on="dk")
    return (j.aggregate(["tag"], [("v", "sum", "s")])
             .sort(["s", "tag"], ascending=[False, True]).limit(3).build())


def _mini_inputs(n=400, seed=0):
    rng = np.random.default_rng(seed)
    facts = Table([Column.from_numpy(rng.integers(0, 30, n)
                                     .astype(np.int64)),
                   Column.from_numpy(rng.integers(0, 100, n)
                                     .astype(np.int64))],
                  names=["k", "v"])
    dims = Table([Column.from_numpy(np.arange(30, dtype=np.int64)),
                  Column.from_numpy(rng.integers(0, 6, 30)
                                    .astype(np.int64))],
                 names=["dk", "tag"])
    return {"facts": facts, "dims": dims}


def test_executor_stamps_kernels_and_renders():
    plan, inputs = _mini_plan(), _mini_inputs()
    res = PlanExecutor(mode="eager").execute(plan, inputs)
    stamped = {m.kind: m.kernel for m in res.metrics.values() if m.kernel}
    assert stamped.get("HashJoin") == "xla:hash_join"
    assert stamped.get("HashAggregate") == "scatter:groupby"
    assert stamped.get("TopK") == "xla:topk"    # Sort+Limit fused by rules
    assert "kernel: xla:hash_join" in res.profile_text()
    assert res.metrics[res.plan.root.label] is not None
    # explain carries the registry floor line
    txt = PlanExecutor(mode="eager").explain(plan, optimized=True,
                                             inputs=inputs)
    assert "kernels [" in txt


def test_forced_pallas_end_to_end_parity(monkeypatch):
    plan, inputs = _mini_plan(), _mini_inputs()
    ref = PlanExecutor(mode="eager").execute(plan, inputs)
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS",
                       "hash_join=pallas,topk=pallas,fused_select=pallas")
    got_e = PlanExecutor(mode="eager").execute(plan, inputs)
    assert ref.table.to_pydict() == got_e.table.to_pydict()
    stamped = {m.kind: m.kernel for m in got_e.metrics.values() if m.kernel}
    assert stamped.get("HashJoin") == "pallas:hash_join"
    assert stamped.get("TopK") == "pallas:topk"
    got_c = PlanExecutor(mode="capped").execute(plan, inputs)
    assert ref.table.to_pydict() == got_c.compact().to_pydict()
    stamped_c = {m.kind: m.kernel
                 for m in got_c.metrics.values() if m.kernel}
    assert stamped_c.get("TopK") == "pallas:topk"


def test_unsupported_signature_runs_fallback_without_error(monkeypatch):
    # string join keys with pallas FORCED: the signature declines at
    # lookup time and the plan still runs on the fallback
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS",
                       "hash_join=pallas,topk=pallas,fused_select=pallas")
    b = PlanBuilder()
    l = b.scan("l", schema=["s", "v"])
    r = b.scan("r", schema=["rs"])
    plan = l.join(r, left_on="s", right_on="rs").build()
    lt = Table([Column.from_pylist([b"a", b"b", b"a", b"c"], dtypes.STRING),
                Column.from_numpy(np.arange(4, dtype=np.int64))],
               names=["s", "v"])
    rt = Table([Column.from_pylist([b"a", b"c"], dtypes.STRING)],
               names=["rs"])
    res = PlanExecutor(mode="eager").execute(plan, {"l": lt, "r": rt})
    assert res.table.num_rows == 3
    join_m = next(m for m in res.metrics.values() if m.kind == "HashJoin")
    assert join_m.kernel == "xla:hash_join"


def test_capped_jit_cache_misses_on_knob_change(monkeypatch):
    plan, inputs = _mini_plan(), _mini_inputs()
    ex = PlanExecutor(mode="capped")
    r1 = ex.execute(plan, inputs)
    r2 = ex.execute(plan, inputs)
    assert r2.jit_cache_hits > 0
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "hash_join=pallas")
    r3 = ex.execute(plan, inputs)
    assert r3.jit_cache_hits == 0           # knob is part of the cache key
    assert r1.compact().to_pydict() == r3.compact().to_pydict()
    r4 = ex.execute(plan, inputs)
    assert r4.jit_cache_hits > 0            # same knob hits again


def test_fused_select_through_executor(monkeypatch):
    # a Filter+Project pair the optimizer fuses into FusedSelect with an
    # int32 predicate column — the shape the Pallas kernel accepts
    b = PlanBuilder()
    t = (b.scan("t", schema=["a", "b", "v"])
          .filter((col("a") > 10) & (col("b") != 0))
          .project([("v2", col("v")), ("a", col("a"))]))
    plan = t.build()
    rng = np.random.default_rng(11)
    n = 600
    tab = Table([Column.from_numpy(rng.integers(0, 20, n)
                                   .astype(np.int32)),
                 Column.from_numpy(rng.integers(-2, 2, n)
                                   .astype(np.int32)),
                 Column.from_numpy(rng.integers(-10**9, 10**9, n)
                                   .astype(np.int64),
                                   validity=rng.random(n) > 0.1)],
                names=["a", "b", "v"])
    ref = PlanExecutor(mode="eager").execute(plan, {"t": tab})
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNELS", "fused_select=pallas")
    got = PlanExecutor(mode="eager").execute(plan, {"t": tab})
    assert ref.table.to_pydict() == got.table.to_pydict()
    fs = [m for m in got.metrics.values() if m.kind == "FusedSelect"]
    assert fs and fs[0].kernel == "pallas:fused_select"
