"""Copying-op tests: concat/slice/split/replace_nulls/if_else/distinct
(the cudf copying surface; split is the SplitAndRetry batch primitive —
RmmSpark.java:461-490)."""
import numpy as np
import pytest

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import Column, Table, dtypes
from spark_rapids_tpu.ops import (concat_columns, concat_tables,
                                  drop_duplicates, halve_table, if_else,
                                  replace_nulls, slice_table, split_table)


def col(values, dtype=None, nulls=None):
    c = Column.from_numpy(np.asarray(values, dtype=dtype))
    if nulls is not None:
        import jax.numpy as jnp
        c = c.with_validity(jnp.asarray(~np.asarray(nulls)))
    return c


def scol(values):
    return Column.from_pylist(values, dtypes.STRING)


def test_concat_fixed_and_strings():
    a = col([1, 2], np.int64, nulls=[False, True])
    b = col([3], np.int64)
    assert concat_columns([a, b]).to_pylist() == [1, None, 3]
    s = concat_columns([scol(["x", None]), scol([""]), scol(["yz"])])
    assert s.to_pylist() == ["x", None, "", "yz"]


def test_concat_tables_and_dtype_mismatch():
    t1 = Table([col([1], np.int64)], names=["a"])
    t2 = Table([col([2], np.int64)], names=["a"])
    assert concat_tables([t1, t2])["a"].to_pylist() == [1, 2]
    with pytest.raises(TypeError):
        concat_columns([col([1], np.int64), col([1.0], np.float64)])


def test_slice_split_halve():
    t = Table([col(np.arange(10), np.int64), scol([str(i) for i in range(10)])],
              names=["x", "s"])
    assert slice_table(t, 2, 5)["x"].to_pylist() == [2, 3, 4]
    parts = split_table(t, [3, 7])
    assert [p.num_rows for p in parts] == [3, 4, 3]
    assert parts[1]["s"].to_pylist() == ["3", "4", "5", "6"]
    halves = halve_table(t)
    assert [h.num_rows for h in halves] == [5, 5]
    # round trip: concat(split(t)) == t
    back = concat_tables(parts)
    assert back["x"].to_pylist() == t["x"].to_pylist()
    assert back["s"].to_pylist() == t["s"].to_pylist()
    with pytest.raises(ValueError):
        split_table(t, [7, 3])


def test_replace_nulls():
    c = col([1, 0, 3], np.int64, nulls=[False, True, False])
    out = replace_nulls(c, -1)
    assert out.to_pylist() == [1, -1, 3] and out.validity is None
    s = replace_nulls(scol(["ab", None, "c", None]), "N/A")
    assert s.to_pylist() == ["ab", "N/A", "c", "N/A"]
    plain = col([1, 2], np.int64)
    assert replace_nulls(plain, 9) is plain


def test_if_else_spark_null_predicate():
    mask = col([True, False, True], nulls=[False, False, True])
    lhs = col([1, 2, 3], np.int64)
    rhs = col([10, 20, 30], np.int64)
    out = if_else(mask, lhs, rhs)
    # null predicate -> ELSE branch (Spark CASE WHEN)
    assert out.to_pylist() == [1, 20, 30]


def test_if_else_null_sides_and_strings():
    mask = col([True, False])
    lhs = scol(["yes", "yes"])
    rhs = scol([None, "no"])
    assert if_else(mask, lhs, rhs).to_pylist() == ["yes", "no"]
    out = if_else(col([False, True]), scol(["a", "b"]), scol([None, "zz"]))
    assert out.to_pylist() == [None, "b"]


def test_drop_duplicates_keeps_first_in_row_order():
    t = Table([col([3, 1, 3, 2, 1], np.int64),
               scol(["a", "b", "c", "d", "e"])], names=["k", "v"])
    out = drop_duplicates(t, ["k"])
    # first occurrences: rows 0 (k=3), 1 (k=1), 3 (k=2), in original order
    assert out["k"].to_pylist() == [3, 1, 2]
    assert out["v"].to_pylist() == ["a", "b", "d"]


def test_empty_inputs_everywhere():
    # empty batches flow through groupby/join/distinct without crashing
    from spark_rapids_tpu.ops import groupby_aggregate, inner_join
    empty = Table([col([], np.int64), col([], np.int64)], names=["k", "v"])
    g = groupby_aggregate(empty, ["k"], [("v", "sum")])
    assert g.num_rows == 0
    lmap, rmap = inner_join([empty["k"]], [empty["k"]])
    assert lmap.length == 0 and rmap.length == 0
    assert drop_duplicates(empty).num_rows == 0
    assert concat_tables([empty, empty]).num_rows == 0
    assert split_table(empty, []) [0].num_rows == 0


def test_drop_duplicates_all_columns_with_nulls():
    t = Table([col([1, 1, 1], np.int64, nulls=[False, False, False]),
               col([5, 5, 6], np.int64, nulls=[True, True, False])],
              names=["a", "b"])
    out = drop_duplicates(t)
    assert out.num_rows == 2
    assert out["b"].to_pylist() == [None, 6]
