"""Hash kernel tests: Spark golden vectors (from real Spark runs, mirrored in
the reference's tests/hash.cpp) + randomized comparison against the pure-
Python oracle."""
import random
import struct

import numpy as np
import pytest

from spark_rapids_tpu import dtypes
from spark_rapids_tpu.columnar import Column
from spark_rapids_tpu.ops.hash import murmur_hash3_32, xxhash64

import spark_hash_oracle as oracle

F32 = np.finfo(np.float32)
F64 = np.finfo(np.float64)
I32, I64 = np.iinfo(np.int32), np.iinfo(np.int64)

# The fifth test string contains unpaired UTF-16 surrogates U+D720 U+D721,
# which Spark stores as their raw 3-byte UTF-8-style encodings.
PUNCT = ("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~").encode() + \
    "휠휡".encode("utf-8", "surrogatepass")
STRINGS5 = [b"", b"The quick brown fox", b"jumps over the lazy dog.",
            b"All work and no play makes Jack a dull boy", PUNCT]

DEC128_VALS = [
    0, 100, -1,
    int.from_bytes(struct.pack(">QQ", 0xFFFFFFFFFCC4D1C3, 0x602F7FC318000001), "big", signed=True),
    int.from_bytes(struct.pack(">QQ", 0x0785EE10D5DA46D9, 0x00F4369FFFFFFFFF), "big", signed=True),
]


def col(vals, dt):
    return Column.from_pylist(vals, dt)


def assert_hashes(result: Column, expected):
    np.testing.assert_array_equal(np.asarray(result.data), np.array(expected))


# ---------------------------------------------------------------------------
# murmur3_32 golden vectors (Spark output, seed 42 unless noted)
# ---------------------------------------------------------------------------
class TestMurmurGolden:
    def test_strings_seed42(self):
        c = col(STRINGS5, dtypes.STRING)
        assert_hashes(murmur_hash3_32([c], 42),
                      [142593372, 1217302703, -715697185, -2061143941, -111635966])

    def test_strings_seed314(self):
        c = col(STRINGS5, dtypes.STRING)
        assert_hashes(murmur_hash3_32([c], 314),
                      [1467149710, 723257560, -1620282500, -2001858707, 1588473657])

    def test_doubles(self):
        c = col([0., -0., -np.nan, F64.min, F64.max], dtypes.FLOAT64)
        assert_hashes(murmur_hash3_32([c], 42),
                      [-1670924195, -853646085, -1281358385, 1897734433, -508695674])

    def test_floats(self):
        c = col([0., -0., -np.nan, F32.min, F32.max], dtypes.FLOAT32)
        assert_hashes(murmur_hash3_32([c], 42),
                      [933211791, 723455942, -349261430, -1225560532, -338752985])

    def test_longs(self):
        c = col([0, 100, -100, I64.min, I64.max], dtypes.INT64)
        assert_hashes(murmur_hash3_32([c], 42),
                      [-1670924195, 1114849490, 904948192, -853646085, -1604625029])

    def test_ints(self):
        c = col([0, 100, -100, I32.min, I32.max], dtypes.INT32)
        assert_hashes(murmur_hash3_32([c], 42),
                      [933211791, 751823303, -1080202046, 723455942, 133916647])

    def test_shorts(self):
        c = col([0, 100, -100, -32768, 32767], dtypes.INT16)
        assert_hashes(murmur_hash3_32([c], 42),
                      [933211791, 751823303, -1080202046, -1871935946, 1249274084])

    def test_bytes(self):
        c = col([0, 100, -100, -128, 127], dtypes.INT8)
        assert_hashes(murmur_hash3_32([c], 42),
                      [933211791, 751823303, -1080202046, 1110053733, 1135925485])

    def test_bools(self):
        c = col([False, True, True, True, False], dtypes.BOOL)
        assert_hashes(murmur_hash3_32([c], 42),
                      [933211791, -559580957, -559580957, -559580957, 933211791])

    def test_timestamps(self):
        c = col([0, 100, -100, -(I64.min // -1000000), I64.max // 1000000],
                dtypes.TIMESTAMP_US)
        assert_hashes(murmur_hash3_32([c], 42),
                      [-1670924195, 1114849490, 904948192, -1832979433, 1752430209])

    def test_dates(self):
        c = col([0, 100, -100, -((2**31) // 100), (2**31 - 1) // 100], dtypes.DATE32)
        assert_hashes(murmur_hash3_32([c], 42),
                      [933211791, 751823303, -1080202046, -1906567553, -1503850410])

    def test_decimal32(self):
        c = col([0, 100, -100, -999999999, 999999999], dtypes.decimal(9, 3))
        assert_hashes(murmur_hash3_32([c], 42),
                      [-1670924195, 1114849490, 904948192, -1454351396, -193774131])

    def test_decimal64(self):
        c = col([0, 100, -100, -999999999999999999, 999999999999999999],
                dtypes.decimal(18, 7))
        assert_hashes(murmur_hash3_32([c], 42),
                      [-1670924195, 1114849490, 904948192, 1962370902, -1795328666])

    def test_decimal128(self):
        c = col(DEC128_VALS, dtypes.decimal(38, 11))
        assert_hashes(murmur_hash3_32([c], 42),
                      [-783713497, -295670906, 1398487324, -52622807, -1359749815])

    def test_structs(self):
        a = col([0, 100, -100, 0x12345678, -0x76543210], dtypes.INT32)
        b = col(["a", "bc", "def", "ghij", "klmno"], dtypes.STRING)
        x = col([0., 100., -100., np.inf, -np.inf], dtypes.FLOAT32)
        y = col([0, 100, -100, 0x0123456789ABCDEF, -0x0123456789ABCDEF], dtypes.INT64)
        inner = Column.make_struct(x=x, y=y)
        structs = Column.make_struct(a=a, b=b, c=inner)
        assert_hashes(murmur_hash3_32([structs], 42),
                      [-105406170, 90479889, -678041645, 1667387937, 301478567])

    def test_combined_chained(self):
        cols = [
            Column.make_struct(
                a=col([0, 100, -100, 0x12345678, -0x76543210], dtypes.INT32),
                b=col(["a", "bc", "def", "ghij", "klmno"], dtypes.STRING),
                c=Column.make_struct(
                    x=col([0., 100., -100., np.inf, -np.inf], dtypes.FLOAT32),
                    y=col([0, 100, -100, 0x0123456789ABCDEF, -0x0123456789ABCDEF],
                          dtypes.INT64))),
            col(STRINGS5, dtypes.STRING),
            col([0., -0., -np.nan, F64.min, F64.max], dtypes.FLOAT64),
            col([0, 100, -100, -(I64.min // -1000000), I64.max // 1000000],
                dtypes.TIMESTAMP_US),
            col([0, 100, -100, -999999999999999999, 999999999999999999],
                dtypes.decimal(18, 7)),
            col([0, 100, -100, I64.min, I64.max], dtypes.INT64),
            col([0., -0., -np.nan, F32.min, F32.max], dtypes.FLOAT32),
            col([0, 100, -100, -((2**31) // 100), (2**31 - 1) // 100], dtypes.DATE32),
            col([0, 100, -100, -999999999, 999999999], dtypes.decimal(9, 3)),
            col([0, 100, -100, I32.min, I32.max], dtypes.INT32),
            col([0, 100, -100, -32768, 32767], dtypes.INT16),
            col([0, 100, -100, -128, 127], dtypes.INT8),
            col([False, True, True, True, False], dtypes.BOOL),
            col(DEC128_VALS, dtypes.decimal(38, 11)),
        ]
        assert_hashes(murmur_hash3_32(cols, 42),
                      [401603227, 588162166, 552160517, 1132537411, -326043017])

    def test_list_of_struct_rejected(self):
        st = Column.make_struct(v=col([1, 2, 3], dtypes.INT32))
        lst = Column.make_list(np.array([0, 1, 3], np.int32), st)
        with pytest.raises(TypeError):
            murmur_hash3_32([lst], 42)

    def test_empty_input_rejected(self):
        with pytest.raises(ValueError):
            murmur_hash3_32([], 42)


# ---------------------------------------------------------------------------
# xxhash64 golden vectors (Spark output, seed 42); rows 6 is null -> seed
# ---------------------------------------------------------------------------
V8 = [True] * 5 + [False] + [True] * 2


def colv(vals, dt):
    vals = list(vals)
    return Column.from_pylist(
        [v if V8[i] else None for i, v in enumerate(vals)], dt)


class TestXXHash64Golden:
    def test_strings(self):
        c = colv(STRINGS5 + [b"", b"abcdefgh", b"abcdefghi"], dtypes.STRING)
        assert_hashes(xxhash64([c], 42),
                      [-7444071767201028348, -3617261401988713833, 8198945020833482635,
                       -5346617152005100141, 6614298085531227868, 42,
                       2470326616177429180, -7093207067522615973])

    def test_doubles(self):
        c = colv([0., -0., -np.nan, F64.min, F64.max, 0., 100., 200.], dtypes.FLOAT64)
        assert_hashes(xxhash64([c], 42),
                      [-5252525462095825812, -5252525462095825812, -3127944061524951246,
                       9065082843545458248, -4222314252576420879, 42,
                       -7996023612001835843, -8838535416664833914])

    def test_floats(self):
        c = colv([0., -0., -np.nan, F32.min, F32.max, 0., np.inf, -np.inf],
                 dtypes.FLOAT32)
        assert_hashes(xxhash64([c], 42),
                      [3614696996920510707, 3614696996920510707, 2692338816207849720,
                       -8545425418825163117, -1065250890878313112, 42,
                       -5940311692336719973, -7580553461823983095])

    def test_longs(self):
        c = colv([0, 100, -100, I64.min, I64.max, 0, 0x123456789ABCDEF,
                  -0x123456789ABCDEF], dtypes.INT64)
        assert_hashes(xxhash64([c], 42),
                      [-5252525462095825812, 8713583529807266080, 5675770457807661948,
                       -8619748838626508300, -3246596055638297850, 42,
                       1941233597257011502, -1318946533059658749])

    def test_ints(self):
        c = colv([0, 100, -100, I32.min, I32.max, 0, -200, -300], dtypes.INT32)
        assert_hashes(xxhash64([c], 42),
                      [3614696996920510707, -7987742665087449293, 8990748234399402673,
                       2073849959933241805, 1508894993788531228, 42,
                       -953008374380745918, 2895908635257747121])

    def test_shorts(self):
        c = colv([0, 100, -100, -32768, 32767, 0, -200, -300], dtypes.INT16)
        assert_hashes(xxhash64([c], 42),
                      [3614696996920510707, -7987742665087449293, 8990748234399402673,
                       -904511417458573795, 8952525448871805501, 42,
                       -953008374380745918, 2895908635257747121])

    def test_bytes(self):
        c = colv([0, 100, -100, -128, 127, 0, -90, -80], dtypes.INT8)
        assert_hashes(xxhash64([c], 42),
                      [3614696996920510707, -7987742665087449293, 8990748234399402673,
                       4160238337661960656, 8632298611707923906, 42,
                       -4008061843281999337, 6690883199412647955])

    def test_bools(self):
        c = colv([False, True, True, True, False, False, False, False], dtypes.BOOL)
        assert_hashes(xxhash64([c], 42),
                      [3614696996920510707, -6698625589789238999, -6698625589789238999,
                       -6698625589789238999, 3614696996920510707, 42,
                       3614696996920510707, 3614696996920510707])

    def test_dates(self):
        c = colv([0, 100, -100, -((2**31) // 100), (2**31 - 1) // 100, 0, -200, -300],
                 dtypes.DATE32)
        assert_hashes(xxhash64([c], 42),
                      [3614696996920510707, -7987742665087449293, 8990748234399402673,
                       -8442426365007754391, -1447590449373190349, 42,
                       -953008374380745918, 2895908635257747121])

    def test_decimal32(self):
        c = colv([0, 100, -100, -999999999, 999999999, 0, -200, -300],
                 dtypes.decimal(9, 3))
        assert_hashes(xxhash64([c], 42),
                      [-5252525462095825812, 8713583529807266080, 5675770457807661948,
                       8670643431269007867, 6810183316718625826, 42,
                       7277994511003214036, 6264187449999859617])

    def test_decimal64(self):
        c = colv([0, 100, -100, -999999999999999999, 999999999999999999, 0, 123, 432],
                 dtypes.decimal(18, 7))
        assert_hashes(xxhash64([c], 42),
                      [-5252525462095825812, 8713583529807266080, 5675770457807661948,
                       4265531446127695490, 2162198894918931945, 42,
                       -3178482946328430151, 4788666723486520022])

    def test_decimal128(self):
        c = colv([0, 100, -1, DEC128_VALS[3], DEC128_VALS[4], 0, DEC128_VALS[3],
                  DEC128_VALS[4]], dtypes.decimal(38, 11))
        assert_hashes(xxhash64([c], 42),
                      [-8959994473701255385, 4409375254388155230, -4006032525457443936,
                       -5423362182451591024, 7041733194569950081, 42,
                       -5423362182451591024, 7041733194569950081])

    def test_timestamps(self):
        c = colv([0, 100, -100, -(I64.min // -1000000), I64.max // 1000000, 0, 200, 300],
                 dtypes.TIMESTAMP_US)
        assert_hashes(xxhash64([c], 42),
                      [-5252525462095825812, 8713583529807266080, 5675770457807661948,
                       7123048472642709644, -5141505295506489983, 42,
                       -1244884446866925109, 1772389229253425430])

    def test_combined(self):
        cols = [
            colv(STRINGS5 + [b"", b"abcdefgh", b"abcdefghi"], dtypes.STRING),
            colv([0., -0., -np.nan, F64.min, F64.max, 0., 100., 200.], dtypes.FLOAT64),
            colv([0, 100, -100, -(I64.min // -1000000), I64.max // 1000000, 0, 200, 300],
                 dtypes.TIMESTAMP_US),
            colv([0, 100, -100, -999999999999999999, 999999999999999999, 0, 123, 432],
                 dtypes.decimal(18, 7)),
            colv([0, 100, -100, I64.min, I64.max, 0, 0x123456789ABCDEF,
                  -0x123456789ABCDEF], dtypes.INT64),
            colv([0., -0., -np.nan, F32.min, F32.max, 0., np.inf, -np.inf],
                 dtypes.FLOAT32),
            colv([0, 100, -100, -((2**31) // 100), (2**31 - 1) // 100, 0, -200, -300],
                 dtypes.DATE32),
            colv([0, 100, -100, -999999999, 999999999, 0, -200, -300],
                 dtypes.decimal(9, 3)),
            colv([0, 100, -100, I32.min, I32.max, 0, -200, -300], dtypes.INT32),
            colv([0, 100, -100, -32768, 32767, 0, -200, -300], dtypes.INT16),
            colv([0, 100, -100, -128, 127, 0, -90, -80], dtypes.INT8),
            colv([False, True, True, True, False, False, False, False], dtypes.BOOL),
            colv([0, 100, -1, DEC128_VALS[3], DEC128_VALS[4], 0, DEC128_VALS[3],
                  DEC128_VALS[4]], dtypes.decimal(38, 11)),
        ]
        assert_hashes(xxhash64(cols, 42),
                      [541735645035655239, 9011982951766246298, 3834379147931449211,
                       -5406325166887725795, 7797509897614041972, 42,
                       -9032872913521304524, -604070008711895908])

    def test_nested_rejected(self):
        st = Column.make_struct(v=Column.from_pylist([1], dtypes.INT32))
        with pytest.raises(TypeError):
            xxhash64([st], 42)


# ---------------------------------------------------------------------------
# randomized oracle comparison
# ---------------------------------------------------------------------------
class TestRandomizedOracle:
    def test_strings_random(self):
        rng = random.Random(1234)
        vals = []
        for _ in range(200):
            n = rng.randrange(0, 80)
            vals.append(bytes(rng.randrange(256) for _ in range(n)))
        c = Column.from_pylist(vals, dtypes.STRING)
        for seed in (0, 42, -7):
            got = np.asarray(murmur_hash3_32([c], seed).data)
            exp = [oracle.murmur32_bytes(v, seed) for v in vals]
            np.testing.assert_array_equal(got, exp)
            got64 = np.asarray(xxhash64([c], seed).data)
            exp64 = [oracle.xxhash64_bytes(v, seed & oracle.M64) for v in vals]
            np.testing.assert_array_equal(got64, exp64)

    def test_long_strings_cross_stripe(self):
        """Lengths straddling the 32-byte xxhash64 stripe boundary."""
        vals = [bytes(range(i % 256)) * 3 for i in range(0, 50)] + \
               [b"x" * n for n in (31, 32, 33, 63, 64, 65, 127, 128, 255)]
        c = Column.from_pylist(vals, dtypes.STRING)
        got = np.asarray(xxhash64([c], 42).data)
        exp = [oracle.xxhash64_bytes(v, 42) for v in vals]
        np.testing.assert_array_equal(got, exp)
        gotm = np.asarray(murmur_hash3_32([c], 42).data)
        expm = [oracle.murmur32_bytes(v, 42) for v in vals]
        np.testing.assert_array_equal(gotm, expm)

    def test_ints_random(self):
        rng = np.random.default_rng(99)
        vals = rng.integers(I64.min, I64.max, size=500, dtype=np.int64)
        c = Column.from_numpy(vals)
        got = np.asarray(murmur_hash3_32([c], 0).data)
        exp = [oracle.murmur32_bytes(oracle.encode_int8(int(v)), 0) for v in vals]
        np.testing.assert_array_equal(got, exp)

    def test_decimal128_random(self):
        rng = random.Random(7)
        vals = [rng.randrange(-(1 << 127), 1 << 127) for _ in range(200)] + \
               [0, 1, -1, 127, 128, -128, -129, 255, 256, -(1 << 127), (1 << 127) - 1]
        c = Column.from_pylist(vals, dtypes.decimal(38, 0))
        got = np.asarray(murmur_hash3_32([c], 42).data)
        exp = [oracle.murmur32_bytes(oracle.encode_decimal128(v), 42) for v in vals]
        np.testing.assert_array_equal(got, exp)
        got64 = np.asarray(xxhash64([c], 42).data)
        exp64 = [oracle.xxhash64_bytes(oracle.encode_decimal128(v), 42) for v in vals]
        np.testing.assert_array_equal(got64, exp64)

    def test_nulls_pass_seed(self):
        c1 = Column.from_pylist([1, None, 3], dtypes.INT32)
        c2 = Column.from_pylist([None, None, 7], dtypes.INT64)
        got = np.asarray(murmur_hash3_32([c1, c2], 42).data)
        # row 0: col1 hashes, col2 null -> unchanged
        h0 = oracle.murmur32_bytes(oracle.encode_int4(1), 42)
        assert got[0] == h0
        # row 1: both null -> seed itself
        assert got[1] == 42
        # row 2: chain
        h2 = oracle.murmur32_bytes(oracle.encode_int4(3), 42)
        h2 = oracle.murmur32_bytes(oracle.encode_int8(7), h2 & oracle.M32)
        assert got[2] == h2


class TestListHashing:
    def test_list_of_ints_matches_flat_chain(self):
        """Spark semantics: hash of [1,2] == chained hash of elements."""
        child = Column.from_pylist([1, 2, 3, 4, 5, 6], dtypes.INT32)
        lst = Column.make_list(np.array([0, 2, 2, 6], np.int32), child)
        got = np.asarray(murmur_hash3_32([lst], 42).data)
        h0 = oracle.murmur32_bytes(oracle.encode_int4(1), 42)
        h0 = oracle.murmur32_bytes(oracle.encode_int4(2), h0 & oracle.M32)
        assert got[0] == h0
        assert got[1] == 42  # empty list -> seed
        h2 = 42
        for v in (3, 4, 5, 6):
            h2 = oracle.murmur32_bytes(oracle.encode_int4(v), h2 & oracle.M32)
        assert got[2] == h2

    def test_list_null_elements_skipped(self):
        child = Column.from_pylist([1, None, 2], dtypes.INT32)
        lst = Column.make_list(np.array([0, 3], np.int32), child)
        got = np.asarray(murmur_hash3_32([lst], 42).data)
        h = oracle.murmur32_bytes(oracle.encode_int4(1), 42)
        h = oracle.murmur32_bytes(oracle.encode_int4(2), h & oracle.M32)
        assert got[0] == h

    def test_list_of_strings(self):
        child = Column.from_pylist(["ab", "cde", "f"], dtypes.STRING)
        lst = Column.make_list(np.array([0, 2, 3], np.int32), child)
        got = np.asarray(murmur_hash3_32([lst], 7).data)
        h0 = oracle.murmur32_bytes(b"ab", 7)
        h0 = oracle.murmur32_bytes(b"cde", h0 & oracle.M32)
        assert got[0] == h0
        assert got[1] == oracle.murmur32_bytes(b"f", 7)


class TestReviewRegressions:
    def test_list_of_decimal128(self):
        child = Column.from_pylist([1, -1, 10**30], dtypes.decimal(38, 0))
        lst = Column.make_list(np.array([0, 2, 3], np.int32), child)
        got = np.asarray(murmur_hash3_32([lst], 42).data)
        h0 = oracle.murmur32_bytes(oracle.encode_decimal128(1), 42)
        h0 = oracle.murmur32_bytes(oracle.encode_decimal128(-1), h0 & oracle.M32)
        assert got[0] == h0
        assert got[1] == oracle.murmur32_bytes(oracle.encode_decimal128(10**30), 42)

    def test_hash_traces_under_jit(self):
        import jax
        c = Column.from_pylist(["spark", "tpu", None, "columnar"], dtypes.STRING)
        i = Column.from_pylist([1, 2, 3, 4], dtypes.INT64)

        @jax.jit
        def f(cc, ii):
            return (murmur_hash3_32([cc, ii], 42, pad_to=16).data,
                    xxhash64([cc, ii], 42, pad_to=16).data)

        m, x = f(c, i)
        me = np.asarray(murmur_hash3_32([c, i], 42).data)
        xe = np.asarray(xxhash64([c, i], 42).data)
        np.testing.assert_array_equal(np.asarray(m), me)
        np.testing.assert_array_equal(np.asarray(x), xe)

    def test_list_traces_under_jit(self):
        import jax
        child = Column.from_pylist([1, 2, 3, 4, 5], dtypes.INT32)
        lst = Column.make_list(np.array([0, 2, 5], np.int32), child)

        @jax.jit
        def f(l):
            return murmur_hash3_32([l], 42, max_span=8).data

        np.testing.assert_array_equal(
            np.asarray(f(lst)), np.asarray(murmur_hash3_32([lst], 42).data))
