"""Reference-shaped facade (api.py): every Java facade class/method from the
reference maps onto our ops and round-trips a minimal call.

These are wiring tests — op semantics are covered by the per-op test files.
"""
import pytest

from spark_rapids_tpu import Column, Table, api, dtypes


def _strings(vals):
    return Column.from_pylist(vals, dtypes.STRING)


def test_cast_strings():
    c = _strings(["42", " -7 ", "bad"])
    out = api.CastStrings.toInteger(c, False, dtypes.INT32)
    assert out.to_pylist() == [42, -7, None]
    f = api.CastStrings.toFloat(_strings(["1.5", "inf"]), False, dtypes.FLOAT64)
    assert f.to_pylist() == [1.5, float("inf")]
    d = api.CastStrings.toDecimal(_strings(["12.34"]), False, 6, 2)
    assert d.to_pylist() == [1234]        # unscaled, decimal32(6,2)
    s = api.CastStrings.fromFloat(
        Column.from_pylist([1.0], dtypes.FLOAT32))
    assert s.to_pylist() == ["1.0"]
    hexed = api.CastStrings.fromIntegersWithBase(
        Column.from_pylist([255], dtypes.INT32), 16)
    assert hexed.to_pylist() == ["FF"]   # Spark conv is uppercase
    back = api.CastStrings.toIntegersWithBase(_strings(["ff"]), 16, False,
                                              dtypes.INT32)
    assert back.to_pylist() == [255]


def test_decimal_utils():
    a = api.CastStrings.toDecimal(_strings(["2.50"]), False, 38, 2)
    b = api.CastStrings.toDecimal(_strings(["4.00"]), False, 38, 2)
    overflow, result = api.DecimalUtils.multiply128(a, b, 4)
    assert overflow.to_pylist() == [False]
    assert result.to_pylist() == [100000]    # unscaled, scale 4
    overflow, q = api.DecimalUtils.integerDivide128(a, b)
    assert q.to_pylist() == [0]


def test_hash():
    c = Column.from_pylist([1, 2], dtypes.INT64)
    h32 = api.Hash.murmurHash32([c], seed=42)
    h64 = api.Hash.xxhash64([c])
    assert h32.dtype.kind == dtypes.Kind.INT32
    assert h64.dtype.kind == dtypes.Kind.INT64


def test_bloom_filter_including_serialized_probe():
    c = Column.from_pylist([10, 20, 30], dtypes.INT64)
    bf = api.BloomFilter.create(3, 8 << 10)
    bf = api.BloomFilter.put(bf, c)
    hits = api.BloomFilter.probe(bf, c)
    assert hits.to_pylist() == [True, True, True]
    from spark_rapids_tpu.ops import bloom_filter_serialize
    buf = bloom_filter_serialize(bf)
    hits2 = api.BloomFilter.probe(buf, c)             # serialized overload
    assert hits2.to_pylist() == [True, True, True]
    merged = api.BloomFilter.merge([bf, bf])
    assert api.BloomFilter.probe(merged, c).to_pylist() == [True, True, True]
    # executor-side shape: merge serialized wire buffers (BloomFilter.java:66)
    merged2 = api.BloomFilter.merge([buf, buf])
    assert api.BloomFilter.probe(merged2, c).to_pylist() == [True, True, True]


def test_timezone_db():
    api.GpuTimeZoneDB.cacheDatabase()
    assert api.GpuTimeZoneDB.isSupportedTimeZone("Asia/Shanghai")
    ts = Column.from_pylist([0], dtypes.TIMESTAMP_US)
    utc = api.GpuTimeZoneDB.fromTimestampToUtcTimestamp(ts, "Asia/Shanghai")
    assert utc.to_pylist() == [-8 * 3600 * 1_000_000]
    back = api.GpuTimeZoneDB.fromUtcTimestampToTimestamp(utc, "Asia/Shanghai")
    assert back.to_pylist() == [0]
    api.GpuTimeZoneDB.shutdown()


def test_datetime_rebase():
    d = Column.from_pylist([0], dtypes.DATE32)
    j = api.DateTimeRebase.rebaseGregorianToJulian(d)
    g = api.DateTimeRebase.rebaseJulianToGregorian(j)
    assert g.to_pylist() == [0]


def test_map_utils():
    m = api.MapUtils.extractRawMapFromJsonString(_strings(['{"a": "1"}']))
    assert m.to_pylist() == [[{"key": "a", "value": "1"}]]


def test_parse_uri():
    c = _strings(["https://example.com/x?a=1"])
    assert api.ParseURI.parseURIProtocol(c).to_pylist() == ["https"]
    assert api.ParseURI.parseURIHost(c).to_pylist() == ["example.com"]
    assert api.ParseURI.parseURIQuery(c).to_pylist() == ["a=1"]
    assert api.ParseURI.parseURIQueryWithLiteral(c, "a").to_pylist() == ["1"]
    assert api.ParseURI.parseURIQueryWithColumn(
        c, _strings(["a"])).to_pylist() == ["1"]


def test_histogram():
    v = Column.from_pylist([1.0, 2.0], dtypes.FLOAT64)
    f = Column.from_pylist([3, 4], dtypes.INT64)
    h = api.Histogram.createHistogramIfValid(v, f, True)
    pct = api.Histogram.percentileFromHistogram(h, [0.5], False)
    assert pct.length == 2


def test_zorder_including_zero_column_corners():
    c = Column.from_pylist([1, 2], dtypes.INT32)
    ib = api.ZOrder.interleaveBits(2, c, c)
    assert ib.length == 2
    hi = api.ZOrder.hilbertIndex(4, 2, c, c)
    assert hi.length == 2
    empty_ib = api.ZOrder.interleaveBits(3)
    assert empty_ib.length == 3
    assert empty_ib.to_pylist() == [[], [], []]
    empty_hi = api.ZOrder.hilbertIndex(4, 3)
    assert empty_hi.to_pylist() == [0, 0, 0]


def test_row_conversion_both_variants():
    t = Table([Column.from_pylist([1, None, 3], dtypes.INT32),
               Column.from_pylist([4, 5, 6], dtypes.INT64)])
    [rows] = api.RowConversion.convertToRows(t)
    back = api.RowConversion.convertFromRows(rows, dtypes.INT32, dtypes.INT64)
    assert back[0].to_pylist() == [1, None, 3]
    assert back[1].to_pylist() == [4, 5, 6]
    [rows2] = api.RowConversion.convertToRowsFixedWidthOptimized(t)
    back2 = api.RowConversion.convertFromRowsFixedWidthOptimized(
        rows2, dtypes.INT32, dtypes.INT64)
    assert back2[0].to_pylist() == [1, None, 3]
    with pytest.raises(ValueError):
        api.RowConversion.convertToRowsFixedWidthOptimized(
            Table([Column.from_pylist([1], dtypes.INT32)] * 120))


def test_rmm_spark_lifecycle_and_metrics():
    api.RmmSpark.clearEventHandler()          # idempotent from any state
    api.RmmSpark.setEventHandler()
    try:
        api.RmmSpark.currentThreadIsDedicatedToTask(7)
        from spark_rapids_tpu.runtime.adaptor import current_thread_id
        assert api.RmmSpark.getStateOf(current_thread_id()) == "THREAD_RUNNING"
        api.RmmSpark.taskDone(7)
        assert api.RmmSpark.getAndResetNumRetryThrow(7) == 0
    finally:
        api.RmmSpark.clearEventHandler()


def test_parquet_footer_reexport():
    assert api.ParquetFooter is not None
