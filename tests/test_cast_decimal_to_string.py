"""decimal→string tests. Oracle: Python Decimal.__str__, which implements the
same algorithm as java.math.BigDecimal.toString (both follow the General
Decimal Arithmetic to-scientific-string rules the reference kernel encodes,
cast_decimal_to_string.cu:53-175) — modulo Python using 'E+x' lowercase 'e';
we normalize the oracle to Java's formatting."""
import decimal

import numpy as np
import pytest

from spark_rapids_tpu import dtypes
from spark_rapids_tpu.columnar import Column
from spark_rapids_tpu.ops.cast_decimal_to_string import decimal_to_non_ansi_string


def java_bigdecimal_str(unscaled: int, scale: int) -> str:
    """BigDecimal(unscaled, scale).toString() oracle via python Decimal.
    Tuple construction is exact (no context rounding, unlike scaleb)."""
    digits = tuple(int(c) for c in str(abs(unscaled)))
    d = decimal.Decimal((0 if unscaled >= 0 else 1, digits, -scale))
    # Python prints exponent as E+26/E-7 like Java; ensure uppercase
    return str(d).upper()


def check(unscaled_values, precision, scale):
    dt = dtypes.decimal(precision, scale)
    col = Column.from_pylist(unscaled_values, dt)
    got = decimal_to_non_ansi_string(col).to_pylist()
    want = [None if v is None else java_bigdecimal_str(v, scale)
            for v in unscaled_values]
    assert got == want, f"precision={precision} scale={scale}"


def test_zero_scale_plain():
    check([0, 1, -1, 123456789, -123456789, None], 9, 0)


def test_positive_scale_plain():
    check([0, 5, -5, 12345, -12345, 100, 99999], 9, 2)
    check([0, 5, 123, 100000], 9, 5)


def test_fraction_leading_zeros():
    # |v| < 10^scale → "0.0...d"
    check([1, 7, 10, 99, -1], 9, 6)


def test_scientific_small_adjusted_exponent():
    # adjusted exponent < -6 → scientific (e.g. unscaled 1 at scale 8 = 1E-8)
    check([1, -1, 12, 123], 18, 8)
    check([1], 18, 18)


def test_decimal64_range():
    check([999999999999999999, -999999999999999999, 1, 0], 18, 4)


def test_decimal128():
    vals = [0, 1, -1, 10**37, -(10**37), 12345678901234567890123456789012345678,
            -12345678901234567890123456789012345678, None]
    check(vals, 38, 0)
    check(vals, 38, 10)
    check([1, -1, 99, 10**20], 38, 30)


def test_decimal128_all_scales_random():
    rng = np.random.default_rng(0)
    for scale in (0, 1, 7, 19, 37):
        vals = [int(rng.integers(-10**12, 10**12)) * 10**int(rng.integers(0, 20))
                for _ in range(50)]
        check(vals, 38, scale)


def test_rejects_non_decimal():
    with pytest.raises(TypeError):
        decimal_to_non_ansi_string(Column.from_pylist([1], dtypes.INT32))


def test_bitmask_utils_roundtrip():
    import jax.numpy as jnp
    from spark_rapids_tpu.utils import (pack_validity, unpack_validity,
                                        bitmask_bitwise_or)
    rng = np.random.default_rng(1)
    v = rng.random(37) < 0.5
    packed = pack_validity(jnp.asarray(v))
    assert packed.shape[0] == 5
    assert np.asarray(unpack_validity(packed, 37)).tolist() == v.tolist()
    a = pack_validity(jnp.asarray(np.array([True, False, False])))
    b = pack_validity(jnp.asarray(np.array([False, False, True])))
    merged = bitmask_bitwise_or([a, b])
    assert np.asarray(unpack_validity(merged, 3)).tolist() == [True, False, True]
