"""Arrow interchange tests — the JVM-facing binding surface (SURVEY.md §1:
the reference's L5 facade passes column handles over JNI; here whole tables
cross the Arrow C Data Interface)."""
import decimal

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import Column, Table, dtypes
from spark_rapids_tpu.interop import (export_to_c, from_arrow, import_from_c,
                                      to_arrow)


def _table():
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    n = 257                                  # not a multiple of 8: bitpacking
    ints = Column.from_numpy(rng.integers(-10**12, 10**12, n))
    nulls = jnp.asarray(rng.random(n) > 0.2)
    floats = Column.from_numpy(rng.standard_normal(n)).with_validity(nulls)
    strs = Column.from_pylist(
        [None if i % 7 == 0 else f"val-{i}-é" for i in range(n)],
        dtypes.STRING)
    bools = Column.from_numpy(rng.integers(0, 2, n).astype(bool))
    return Table([ints, floats, strs, bools], names=["i", "f", "s", "b"])


def test_round_trip_via_pyarrow():
    t = _table()
    back = from_arrow(to_arrow(t))
    for name in t.names:
        assert back[name].to_pylist() == t[name].to_pylist(), name


def test_to_arrow_values_match():
    t = _table()
    at = to_arrow(t)
    assert at.column("i").to_pylist() == t["i"].to_pylist()
    assert at.column("s").to_pylist() == t["s"].to_pylist()
    assert at.column("b").to_pylist() == t["b"].to_pylist()


def test_decimal128_round_trip():
    from spark_rapids_tpu.ops import string_to_decimal
    c = string_to_decimal(
        Column.from_pylist(["12345678901234567890.123", None, "-0.001"],
                           dtypes.STRING), precision=38, scale=3)
    t = Table([c], names=["d"])
    at = to_arrow(t)
    assert at.column("d").to_pylist() == [
        decimal.Decimal("12345678901234567890.123"), None,
        decimal.Decimal("-0.001")]
    back = from_arrow(at)
    assert back["d"].to_pylist() == c.to_pylist()
    assert back["d"].dtype.scale == 3


def test_small_decimals_widen_and_narrow():
    import jax.numpy as jnp
    c = Column(dtype=dtypes.DType(dtypes.Kind.DECIMAL64, precision=12, scale=2),
               length=3, data=jnp.asarray(np.array([123, -4500, 0], np.int64)))
    at = to_arrow(Table([c], names=["d"]))
    assert at.column("d").to_pylist() == [decimal.Decimal("1.23"),
                                          decimal.Decimal("-45.00"),
                                          decimal.Decimal("0.00")]
    back = from_arrow(at)
    assert back["d"].dtype.kind == dtypes.Kind.DECIMAL64
    assert back["d"].to_pylist() == [123, -4500, 0]


def test_c_data_interface_round_trip():
    from pyarrow.cffi import ffi
    t = _table()
    c_schema = ffi.new("struct ArrowSchema*")
    c_array = ffi.new("struct ArrowArray*")
    export_to_c(t, int(ffi.cast("uintptr_t", c_array)),
                int(ffi.cast("uintptr_t", c_schema)))
    back = import_from_c(int(ffi.cast("uintptr_t", c_array)),
                         int(ffi.cast("uintptr_t", c_schema)))
    assert list(back.names) == list(t.names)
    for name in t.names:
        assert back[name].to_pylist() == t[name].to_pylist(), name


def test_nullable_bool_import():
    t = from_arrow(pa.table({"b": pa.array([True, None, False])}))
    assert t["b"].to_pylist() == [True, None, False]


def test_decimal256_rejected_not_corrupted():
    at = pa.table({"d": pa.array([decimal.Decimal("1.23")],
                                 pa.decimal256(50, 2))})
    with pytest.raises(TypeError):
        from_arrow(at)


def test_duplicate_column_names_survive_export():
    import jax.numpy as jnp
    a = Column.from_numpy(np.array([1, 2], np.int64))
    b = Column.from_numpy(np.array([3, 4], np.int64))
    at = to_arrow(Table([a, b], names=["k", "k"]))
    assert at.num_columns == 2
    assert at.column(1).to_pylist() == [3, 4]


def test_apply_boolean_mask_rejects_wrong_length():
    from spark_rapids_tpu.ops import apply_boolean_mask
    c = Column.from_numpy(np.arange(5, dtype=np.int64))
    with pytest.raises(ValueError):
        apply_boolean_mask(c, np.ones(8, bool))
    out = apply_boolean_mask(c, np.array([1, 0, 1, 0, 1], bool))
    assert out.to_pylist() == [0, 2, 4]


def test_nested_list_struct_round_trip():
    import jax.numpy as jnp
    # build LIST<STRUCT<key:str, value:str>> — the from_json output shape
    keys = Column.from_pylist(["a", "b", "c"], dtypes.STRING)
    vals = Column.from_pylist(["1", None, "3"], dtypes.STRING)
    struct = Column.make_struct(key=keys, value=vals)
    offsets = jnp.asarray(np.array([0, 2, 2, 3], np.int32))
    lists = Column.make_list(offsets, struct,
                             jnp.asarray([True, False, True]))
    t = Table([lists], names=["m"])
    at = to_arrow(t)
    assert at.column("m").to_pylist() == [
        [{"key": "a", "value": "1"}, {"key": "b", "value": None}],
        None,
        [{"key": "c", "value": "3"}],
    ]
    back = from_arrow(at)
    assert back["m"].to_pylist() == t["m"].to_pylist()


def test_null_list_with_nonempty_extent_does_not_corrupt_neighbor():
    import jax.numpy as jnp
    child = Column.from_numpy(np.array([1, 2, 3, 4], np.int64))
    # null row 1 spans [2,3): its extent must NOT leak into row 0
    lists = Column.make_list(jnp.asarray(np.array([0, 2, 3, 4], np.int32)),
                             child, jnp.asarray([True, False, True]))
    at = to_arrow(Table([lists], names=["l"]))
    assert at.column("l").to_pylist() == [[1, 2], None, [4]]


def test_struct_field_named_validity_imports():
    at = pa.table({"s": pa.array([{"validity": 1}, {"validity": 2}])})
    t = from_arrow(at)
    assert t["s"].to_pylist() == [{"validity": 1}, {"validity": 2}]


def test_zero_field_struct_imports():
    at = pa.table({"s": pa.array([{}, {}], type=pa.struct([]))})
    t = from_arrow(at)
    assert t["s"].length == 2


def test_zero_field_struct_round_trip():
    # export side: StructArray.from_arrays([]) would infer length 0 and
    # silently drop every row
    at = pa.table({"s": pa.array([{}, None, {}], type=pa.struct([]))})
    t = from_arrow(at)
    back = to_arrow(t)
    assert back.num_rows == 3
    assert back.column("s").to_pylist() == [{}, None, {}]


def test_from_json_output_exports_to_arrow():
    from spark_rapids_tpu.ops import from_json
    col = Column.from_pylist(['{"x": 1, "y": "two"}', None, "{}"],
                             dtypes.STRING)
    m = from_json(col)
    at = to_arrow(Table([m], names=["m"]))
    got = at.column("m").to_pylist()
    assert got[0] == [{"key": "x", "value": "1"},
                      {"key": "y", "value": "two"}]
    assert got[2] == []


def test_from_arrow_date_timestamp():
    import datetime
    at = pa.table({
        "d": pa.array([datetime.date(2020, 1, 1), None], pa.date32()),
        "ts": pa.array([datetime.datetime(2021, 6, 1, 12), None],
                       pa.timestamp("us")),
    })
    t = from_arrow(at)
    assert t["d"].dtype == dtypes.DATE32
    assert t["d"].to_pylist() == [18262, None]
    assert t["ts"].dtype == dtypes.TIMESTAMP_US
    back = to_arrow(t)
    assert back.column("ts").to_pylist() == at.column("ts").to_pylist()


def test_uint64_round_trip():
    import jax.numpy as jnp
    # conv()'s unsigned-64 intermediate must cross the Arrow boundary
    c = Column(dtype=dtypes.UINT64, length=3,
               data=jnp.asarray(np.array([0, 2**64 - 510, 510], np.uint64)),
               validity=jnp.asarray([True, True, False]))
    at = to_arrow(Table([c], names=["u"]))
    assert at.schema.field("u").type == pa.uint64()
    back = from_arrow(at)
    assert back["u"].dtype.kind == dtypes.Kind.UINT64
    assert back["u"].to_pylist() == [0, 2**64 - 510, None]
