"""Runtime lock-order witness (runtime/lockdep.py): cycle detection on
the observed graph, RLock reentrancy, the same-class policy, the
Condition wait protocol, factory install/uninstall with the package-
only wrapping gate, and the static-graph divergence report
(docs/analysis.md#concurrency-invariants)."""

import os
import threading

import pytest

from spark_rapids_tpu.runtime import lockdep as ld

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "spark_rapids_tpu")


def _traced(site, wit, rlock=False):
    inner = (ld._real_rlock() if ld.active() else threading.RLock()) \
        if rlock else \
        (ld._real_lock() if ld.active() else threading.Lock())
    return ld._TracedLock(inner, site, wit)


class TestWitness:
    def test_inversion_raises_on_second_order(self):
        wit = ld._Witness()
        a = _traced("a.py:1", wit)
        b = _traced("b.py:2", wit)
        with a:
            with b:
                pass
        with b:
            with pytest.raises(ld.LockOrderViolation) as ei:
                a.acquire()
        msg = str(ei.value)
        assert "a.py:1" in msg and "b.py:2" in msg
        assert "acquired at" in msg          # the new edge's stack
        assert wit.cycles() == ["b.py:2 -> a.py:1 -> b.py:2"]

    def test_violation_rolls_back_cleanly(self):
        """The raising acquire releases the inner lock and leaves the
        held-set consistent — the suite keeps running after a caught
        violation."""
        wit = ld._Witness()
        a = _traced("a.py:1", wit)
        b = _traced("b.py:2", wit)
        with a:
            with b:
                pass
        with b:
            with pytest.raises(ld.LockOrderViolation):
                a.acquire()
        # a's inner lock was released by the rollback; a fresh
        # same-order use works
        with a:
            pass
        assert wit._held() == []

    def test_longer_cycle_through_intermediate(self):
        wit = ld._Witness()
        a, b, c = (_traced(f"{n}.py:1", wit) for n in "abc")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with pytest.raises(ld.LockOrderViolation):
                a.acquire()
        assert wit.cycles() == ["c.py:1 -> a.py:1 -> b.py:1 -> c.py:1"]

    def test_rlock_reentrancy_is_not_an_edge(self):
        wit = ld._Witness()
        r = _traced("r.py:1", wit, rlock=True)
        with r:
            with r:
                pass
        assert wit.edges() == {}
        assert wit._held() == []

    def test_same_class_instances_skip_edge(self):
        """Two locks from ONE construction site (e.g. every LruDict's
        _lru_lock): nesting them records no edge, mirroring the static
        tool — a class-keyed self-edge cannot distinguish legal
        reentrancy from a two-instance inversion."""
        wit = ld._Witness()
        x = _traced("lru.py:40", wit)
        y = _traced("lru.py:40", wit)
        with x:
            with y:
                pass
        with y:
            with x:
                pass                          # would deadlock-cycle if keyed
        assert wit.edges() == {}

    def test_edge_counts_accumulate(self):
        wit = ld._Witness()
        a = _traced("a.py:1", wit)
        b = _traced("b.py:2", wit)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert wit.edges() == {("a.py:1", "b.py:2"): 3}

    def test_condition_wait_drops_and_restores_held_set(self):
        """threading.Condition over a traced lock: wait() releases the
        lock (held-set must forget it — another thread's acquire is
        NOT ordered after it) and re-entry on wakeup re-records edges
        from what the thread still holds."""
        wit = ld._Witness()
        lk = _traced("l.py:1", wit)
        outer = _traced("o.py:2", wit)
        cv = threading.Condition(lk)
        woke = threading.Event()

        def waiter():
            with outer:
                with cv:
                    cv.wait(timeout=5.0)
            woke.set()

        th = threading.Thread(target=waiter)
        th.start()
        # wake it; notify requires holding the condition
        while True:
            with cv:
                # waiter's held-set dropped `lk` while parked, so this
                # acquire sees no o->l ordering from THIS thread
                cv.notify_all()
            if woke.wait(timeout=0.05):
                break
        th.join(5.0)
        assert not th.is_alive()
        # the waiter recorded o.py:2 -> l.py:1 at entry AND again on
        # wakeup re-acquire (both are real ordering events)
        assert wit.edges().get(("o.py:2", "l.py:1"), 0) >= 2
        assert wit.cycles() == []

    def test_release_save_restore_roundtrip_keeps_count(self):
        wit = ld._Witness()
        r = _traced("r.py:1", wit, rlock=True)
        r.acquire()
        r.acquire()
        saved = r._release_save()
        assert wit._held() == []              # fully forgotten
        r._acquire_restore(saved)
        held = wit._held()
        assert len(held) == 1 and held[0][2] == 2
        r.release()
        r.release()
        assert wit._held() == []


class TestInstall:
    def test_factory_wraps_package_code_only(self):
        """After install(), a lock constructed from a file under
        spark_rapids_tpu/ is traced (class = its construction site);
        one constructed from anywhere else stays a real stdlib lock."""
        was_active = ld.active()
        ld.install()
        try:
            ns = {}
            fake = os.path.join(PKG, "fake_lockdep_probe.py")
            code = compile("import threading\n"
                           "LK = threading.Lock()\n"
                           "RLK = threading.RLock()\n", fake, "exec")
            exec(code, ns)
            assert isinstance(ns["LK"], ld._TracedLock)
            assert ns["LK"]._site == \
                "spark_rapids_tpu/fake_lockdep_probe.py:2"
            assert isinstance(ns["RLK"], ld._TracedLock)
            # this test file is OUTSIDE the package: real lock
            outside = threading.Lock()
            assert not isinstance(outside, ld._TracedLock)
            # traced proxies still behave as context managers
            with ns["LK"]:
                assert ns["LK"].locked()
        finally:
            if not was_active:
                ld.uninstall()

    def test_install_is_idempotent_and_uninstall_restores(self):
        if ld.active():
            pytest.skip("lockdep armed session-wide; cannot uninstall")
        real = threading.Lock
        ld.install()
        ld.install()                          # no double-patch
        assert threading.Lock is ld._lock_factory
        ld.uninstall()
        assert threading.Lock is real
        assert not ld.active()
        ld.uninstall()                        # idempotent too


class TestStaticComparison:
    GRAPH = {
        "locks": {"mod:A": "a.py:1", "mod:B": "b.py:2", "mod:C": "c.py:3"},
        "edges": [["mod:A", "mod:B"]],
        "declared": [],
    }

    def _seeded(self, monkeypatch):
        wit = ld._Witness()
        monkeypatch.setattr(ld, "_witness", wit)
        a = _traced("a.py:1", wit)
        b = _traced("b.py:2", wit)
        c = _traced("c.py:3", wit)
        t = _traced("tests/x.py:9", wit)      # not in the lock table
        with a:
            with b:
                pass                          # predicted by static
        with a:
            with c:
                pass                          # NOT predicted: divergence
        with t:
            with b:
                pass                          # unmapped site: excluded
        return wit

    def test_divergence_report(self, monkeypatch):
        self._seeded(monkeypatch)
        rep = ld.compare_to_static(self.GRAPH)
        assert rep["observed"] == 3
        assert rep["mapped"] == ["mod:A -> mod:B"]
        assert rep["missing"] == ["mod:A -> mod:C"]
        assert rep["unmapped"] == ["tests/x.py:9 -> b.py:2"]

    def test_certify_fails_on_missing_edge(self, monkeypatch):
        self._seeded(monkeypatch)
        rep = ld.certify(self.GRAPH)
        assert rep["ok"] is False and rep["cycles"] == []

    def test_certify_ok_when_all_predicted(self, monkeypatch):
        wit = ld._Witness()
        monkeypatch.setattr(ld, "_witness", wit)
        a = _traced("a.py:1", wit)
        b = _traced("b.py:2", wit)
        with a:
            with b:
                pass
        rep = ld.certify(self.GRAPH)
        assert rep["ok"] is True
        assert rep["mapped"] == ["mod:A -> mod:B"]

    def test_real_tree_static_graph_loads(self):
        """The witness's own loader round-trips the linter: the graph
        it compares against has the fleet lock and is non-trivial."""
        g = ld._load_static_graph()
        assert "spark_rapids_tpu/serving/fleet.py:FleetScheduler._lock" \
            in g["locks"]
        assert len(g["edges"]) >= 10
