"""Parquet footer parse/prune/filter tests.

Oracle: pyarrow — footers come from real files pyarrow wrote, and every
filtered footer this code serializes is spliced back into the file and
re-read with pyarrow (the role parquet-avro plays for the reference's
Java tests, SURVEY.md §4).
"""
import io

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.io import (ParquetFooter, StructElement, ListElement,
                                 MapElement, ValueElement)


def write_parquet(table, row_group_size=None) -> bytes:
    sink = io.BytesIO()
    pq.write_table(table, sink, row_group_size=row_group_size,
                   compression="NONE")
    return sink.getvalue()


def footer_bytes(file_bytes: bytes) -> bytes:
    assert file_bytes[-4:] == b"PAR1"
    n = int.from_bytes(file_bytes[-8:-4], "little")
    return file_bytes[-8 - n:-8]


def splice_footer(file_bytes: bytes, serialized: bytes) -> bytes:
    n = int.from_bytes(file_bytes[-8:-4], "little")
    return file_bytes[: len(file_bytes) - 8 - n] + serialized


def simple_table(n=1000):
    return pa.table({
        "a": pa.array(range(n), pa.int64()),
        "b": pa.array([f"s{i}" for i in range(n)], pa.string()),
        "c": pa.array([i * 0.5 for i in range(n)], pa.float64()),
    })


def test_roundtrip_identity():
    data = write_parquet(simple_table())
    schema = StructElement(a=ValueElement(), b=ValueElement(),
                           c=ValueElement())
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, False) as f:
        assert f.get_num_rows() == 1000
        assert f.get_num_columns() == 3
        assert f.get_num_row_groups() == 1
        new = splice_footer(data, f.serialize_thrift_file())
    md = pq.read_metadata(io.BytesIO(new))
    assert md.num_rows == 1000
    assert md.num_columns == 3
    got = pq.read_table(io.BytesIO(new))
    assert got.equals(simple_table())


def test_prune_columns():
    data = write_parquet(simple_table())
    schema = StructElement(c=ValueElement(), a=ValueElement())
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, False) as f:
        assert f.get_num_columns() == 2
        new = splice_footer(data, f.serialize_thrift_file())
    got = pq.read_table(io.BytesIO(new))
    # parquet order retained: a before c
    assert got.column_names == ["a", "c"]
    assert got["a"].to_pylist() == list(range(1000))
    assert got["c"].to_pylist() == [i * 0.5 for i in range(1000)]


def test_case_insensitive_prune():
    data = write_parquet(pa.table({"MixedCase": pa.array([1, 2, 3])}))
    schema = StructElement(mixedcase=ValueElement())
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, True) as f:
        assert f.get_num_columns() == 1
    # case-sensitive: no match -> zero columns
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       StructElement(mixedcase=ValueElement()),
                                       False) as f:
        assert f.get_num_columns() == 0


def test_missing_column_skipped():
    data = write_parquet(simple_table())
    schema = StructElement(a=ValueElement(), zz=ValueElement())
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, False) as f:
        assert f.get_num_columns() == 1


def test_row_group_filter_by_midpoint():
    data = write_parquet(simple_table(10_000), row_group_size=1000)
    md = pq.read_metadata(io.BytesIO(data))
    assert md.num_row_groups == 10
    # compute each group's midpoint the same way Spark does
    fb = footer_bytes(data)
    schema = StructElement(a=ValueElement(), b=ValueElement(),
                           c=ValueElement())
    # whole file -> all groups
    with ParquetFooter.read_and_filter(fb, 0, len(data), schema, False) as f:
        assert f.get_num_row_groups() == 10
        assert f.get_num_rows() == 10_000
    # split covering no midpoints -> nothing
    with ParquetFooter.read_and_filter(fb, len(data) + 10, 5, schema,
                                       False) as f:
        assert f.get_num_row_groups() == 0
        assert f.get_num_rows() == 0
    # half the file -> roughly half the groups; verify exact containment
    starts = []
    sizes = []
    for g in range(10):
        rg = md.row_group(g)
        s = min(
            (rg.column(c).dictionary_page_offset
             if rg.column(c).dictionary_page_offset is not None
             else rg.column(c).data_page_offset)
            for c in range(rg.num_columns))
        starts.append(s)
        sizes.append(sum(rg.column(c).total_compressed_size
                         for c in range(rg.num_columns)))
    half = len(data) // 2
    want = sum(1 for s, z in zip(starts, sizes) if 0 <= s + z // 2 < half)
    with ParquetFooter.read_and_filter(fb, 0, half, schema, False) as f:
        assert f.get_num_row_groups() == want
        new = splice_footer(data, f.serialize_thrift_file())
    got = pq.read_table(io.BytesIO(new))
    assert got.num_rows == want * 1000


def test_nested_struct_prune():
    table = pa.table({
        "s": pa.array([{"x": 1, "y": "a", "z": 2.0}] * 10),
        "p": pa.array(range(10)),
    })
    data = write_parquet(table)
    schema = StructElement(
        s=StructElement(x=ValueElement(), z=ValueElement()))
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, False) as f:
        assert f.get_num_columns() == 1
        new = splice_footer(data, f.serialize_thrift_file())
    got = pq.read_table(io.BytesIO(new))
    assert got.column_names == ["s"]
    assert got["s"].to_pylist() == [{"x": 1, "z": 2.0}] * 10


def test_list_and_map_prune():
    table = pa.table({
        "l": pa.array([[1, 2], [3]], pa.list_(pa.int64())),
        "m": pa.array([[("k", 7)], [("q", 8)]],
                      pa.map_(pa.string(), pa.int64())),
        "v": pa.array([1, 2]),
    })
    data = write_parquet(table)
    schema = StructElement(
        l=ListElement(ValueElement()),
        m=MapElement(ValueElement(), ValueElement()))
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, False) as f:
        assert f.get_num_columns() == 2
        new = splice_footer(data, f.serialize_thrift_file())
    got = pq.read_table(io.BytesIO(new))
    assert got.column_names == ["l", "m"]
    assert got["l"].to_pylist() == [[1, 2], [3]]
    assert got["m"].to_pylist() == [[("k", 7)], [("q", 8)]]


def test_list_of_struct_inner_prune():
    table = pa.table({
        "ls": pa.array([[{"u": 1, "w": 2}], [{"u": 3, "w": 4}]],
                       pa.list_(pa.struct([("u", pa.int64()),
                                           ("w", pa.int64())]))),
    })
    data = write_parquet(table)
    schema = StructElement(ls=ListElement(StructElement(w=ValueElement())))
    with ParquetFooter.read_and_filter(footer_bytes(data), 0, len(data),
                                       schema, False) as f:
        new = splice_footer(data, f.serialize_thrift_file())
    got = pq.read_table(io.BytesIO(new))
    assert got["ls"].to_pylist() == [[{"w": 2}], [{"w": 4}]]


def test_type_mismatch_raises():
    data = write_parquet(simple_table())
    with pytest.raises(ValueError):
        ParquetFooter.read_and_filter(
            footer_bytes(data), 0, len(data),
            StructElement(a=StructElement(x=ValueElement())), False)


def test_garbage_buffer_raises():
    with pytest.raises(ValueError):
        ParquetFooter.read_and_filter(b"\x99\x88\x77", 0, 10,
                                      StructElement(a=ValueElement()), False)


# ---- per-row-group min/max statistics (read_footer_stats) -------------------

def test_footer_stats_per_group_minmax():
    from spark_rapids_tpu.io import read_footer_stats
    data = write_parquet(simple_table(4000), row_group_size=1000)
    stats = read_footer_stats(data)
    assert len(stats) == 4
    for g, rg in enumerate(stats):
        assert rg.index == g
        assert rg.num_rows == 1000
        a = rg.columns["a"]
        assert (a.min, a.max) == (g * 1000, g * 1000 + 999)
        assert a.null_count == 0
        assert a.total_compressed_size > 0
        c = rg.columns["c"]
        assert c.min == pytest.approx(g * 1000 * 0.5)
        assert c.max == pytest.approx((g * 1000 + 999) * 0.5)
        b = rg.columns["b"]            # strings: bytes min/max
        assert isinstance(b.min, bytes) and b.min.startswith(b"s")
    # oracle: pyarrow reads the same statistics back
    md = pq.read_metadata(io.BytesIO(data))
    st = md.row_group(2).column(0).statistics
    assert (stats[2].columns["a"].min, stats[2].columns["a"].max) == \
        (st.min, st.max)


def test_footer_stats_none_safe_without_statistics():
    """A file written without statistics surfaces min/max as None (the
    'cannot prove anything' state pruning must honor) instead of raising."""
    from spark_rapids_tpu.io import read_footer_stats
    sink = io.BytesIO()
    pq.write_table(simple_table(100), sink, compression="NONE",
                   write_statistics=False)
    stats = read_footer_stats(sink.getvalue())
    assert len(stats) == 1
    for st in stats[0].columns.values():
        assert st.min is None and st.max is None
        assert st.total_compressed_size > 0


def test_footer_stats_nested_paths_and_file_source(tmp_path):
    """Nested leaves key by dotted path; a path source reads only the
    footer tail (no whole-file load)."""
    from spark_rapids_tpu.io import read_footer_stats
    table = pa.table({
        "s": pa.array([{"x": i, "y": float(i)} for i in range(50)]),
        "p": pa.array(range(50)),
    })
    path = tmp_path / "nested.parquet"
    pq.write_table(table, path, compression="NONE")
    stats = read_footer_stats(str(path))
    cols = stats[0].columns
    assert cols["s.x"].min == 0 and cols["s.x"].max == 49
    assert cols["s.y"].max == pytest.approx(49.0)
    assert cols["p"].column == "p" and cols["s.x"].column == "s"


def test_footer_stats_garbage_raises():
    from spark_rapids_tpu.io import read_footer_stats
    with pytest.raises(ValueError):
        read_footer_stats(b"\x00" * 64)


def test_select_row_groups_pruning_is_conservative():
    """select_row_groups drops a group only on PROOF of emptiness; missing
    stats, nulls, and type mismatches keep the group."""
    from spark_rapids_tpu.io import read_footer_stats, select_row_groups
    data = write_parquet(simple_table(4000), row_group_size=1000)
    stats = read_footer_stats(data)
    # a in [0, 4000): a < 1500 keeps groups 0-1
    kept, pruned = select_row_groups(stats, [("a", "<", 1500)], 4)
    assert (kept, pruned) == ([0, 1], 2)
    kept, pruned = select_row_groups(stats, [("a", ">=", 3000)], 4)
    assert (kept, pruned) == ([3], 3)
    kept, pruned = select_row_groups(stats, [("a", "==", 2500)], 4)
    assert (kept, pruned) == ([2], 3)
    # conjuncts AND together
    kept, pruned = select_row_groups(
        stats, [("a", ">=", 1000), ("a", "<", 2000)], 4)
    assert (kept, pruned) == ([1], 3)
    # string conjunct compares as bytes
    kept, _ = select_row_groups(stats, [("b", "==", "s1500")], 4)
    assert 1 in kept
    # unknown column / no stats / None stats: keep everything
    assert select_row_groups(stats, [("zz", "<", 0)], 4)[1] == 0
    assert select_row_groups(None, [("a", "<", 0)], 4) == (list(range(4)), 0)
    # type mismatch (string literal vs int column): keep everything
    assert select_row_groups(stats, [("a", "<", "x")], 4)[1] == 0


def test_select_row_groups_null_groups_never_prune():
    """min/max statistics exclude nulls, but null rows carry fill values
    the row-wise Filter still sees — a group with nulls must not prune."""
    from spark_rapids_tpu.io import read_footer_stats, select_row_groups
    t = pa.table({"a": pa.array([None, 5, 6, 7], pa.int64())})
    data = write_parquet(t)
    stats = read_footer_stats(data)
    assert stats[0].columns["a"].null_count == 1
    # min=5: "a < 3" would prune on min/max alone, but the null row's
    # fill value (0) passes the engine's raw-buffer comparison
    kept, pruned = select_row_groups(stats, [("a", "<", 3)], 1)
    assert (kept, pruned) == ([0], 0)
