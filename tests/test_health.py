"""Device health monitor, circuit breaker, and degraded CPU-tier tests.

Unit level: failure taxonomy (transient/sticky/fatal), jittered exponential
backoff against the shared per-plan-attempt retry budget, the breaker state
machine (closed → open → half_open), and arbiter-style get-and-reset metric
drains. End to end: a fatal injected fault mid-plan completes degraded on
the CPU tier with result parity, `reset_device()` arms a half-open probe,
and the probe restores normal execution — the recovery story the fault
injector exists to prove (docs/robustness.md).
"""
import json
import random

import numpy as np
import pytest

import jax.numpy as jnp

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import Column, Table, dtypes, faultinj
from spark_rapids_tpu.plan import PlanBuilder, PlanExecutor, col, lit
from spark_rapids_tpu.runtime.health import (CLOSED, FATAL, HALF_OPEN, OPEN,
                                             STICKY, TRANSIENT,
                                             CircuitBreaker,
                                             DeviceHealthMonitor,
                                             device_probe)


def _col(a):
    a = np.asarray(a, dtype=np.int64)
    return Column(dtype=dtypes.INT64, length=len(a), data=jnp.asarray(a))


def _tables(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    sales = Table([_col(rng.integers(0, 50, n)),
                   _col(rng.integers(1, 100, n))], names=["k", "v"])
    dims = Table([_col(np.arange(50)), _col(np.arange(50) % 3)],
                 names=["dk", "grp"])
    return sales, dims


def _plan():
    b = PlanBuilder()
    s = b.scan("sales", schema=["k", "v"])
    d = b.scan("dims", schema=["dk", "grp"]).filter(col("grp") == 1)
    return (s.join(d, left_on="k", right_on="dk")
             .project({"grp": col("grp"), "rev": col("v") * lit(2)})
             .aggregate(["grp"], [("rev", "sum", "total")])
             .sort(["grp"])
             .build())


def _write_cfg(tmp_path, cfg):
    p = tmp_path / "faultinj.json"
    p.write_text(json.dumps(cfg))
    return str(p)


@pytest.fixture
def _clean_faultinj():
    yield
    faultinj.uninstall()


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _monitor(**kw):
    """Monitor with no real sleeping and a deterministic rng."""
    slept = []
    kw.setdefault("sleep", slept.append)
    kw.setdefault("rng", random.Random(7))
    m = DeviceHealthMonitor(**kw)
    m._test_sleeps = slept
    return m


# ---- taxonomy ---------------------------------------------------------------

def test_classify_fatal_and_transient():
    hm = _monitor()
    assert hm.record_failure("op", faultinj.DeviceFatalError("x")) == FATAL
    assert hm.record_failure("op", faultinj.DeviceAssertError("x")) == TRANSIENT
    assert hm.record_failure("op", faultinj.InjectedReturnCode("op", 2)) \
        == TRANSIENT


def test_classify_sticky_same_op_within_window():
    clock = _FakeClock()
    hm = _monitor(clock=clock, sticky_threshold=3, sticky_window_s=60)
    e = faultinj.DeviceAssertError("x")
    assert hm.record_failure("HashJoin#1", e) == TRANSIENT
    assert hm.record_failure("HashJoin#1", e) == TRANSIENT
    # a different op does not contribute to HashJoin#1's window
    assert hm.record_failure("Sort#2", e) == TRANSIENT
    assert hm.record_failure("HashJoin#1", e) == STICKY


def test_probe_recovery_clears_sticky_window():
    """Cooldown+probe recovery (no reset_device) must also restart the
    windows: a single post-recovery transient may not instantly re-trip."""
    clock = _FakeClock()
    hm = _monitor(clock=clock, sticky_threshold=3, sticky_window_s=60,
                  probe=lambda: True)
    e = faultinj.DeviceAssertError("x")
    hm.record_failure("op", e)
    hm.record_failure("op", e)
    assert hm.record_failure("op", e) == STICKY
    hm.trip(STICKY)
    hm.breaker.half_open()
    assert hm.probe()                         # recovered
    clock.t += 1                              # still inside the old window
    assert hm.record_failure("op", e) == TRANSIENT
    drained = hm.get_and_reset_metrics()
    assert drained["sticky_faults"] == 1      # only the classifying failure
    assert drained["transient_faults"] == 3


def test_success_clears_sticky_window():
    """Absorbed transients must not accumulate across executions: a unit
    that eventually succeeds resets its op's failure window, so sticky
    means repeated failure with NO intervening success."""
    hm = _monitor(sticky_threshold=3)
    e = faultinj.DeviceAssertError("x")
    for _ in range(5):                        # one absorbed fault per "job"
        assert hm.record_failure("HashJoin#1", e) == TRANSIENT
        hm.record_success("HashJoin#1")       # the retry succeeded
    assert hm.record_failure("HashJoin#1", e) == TRANSIENT


def test_sticky_window_ages_out():
    clock = _FakeClock()
    hm = _monitor(clock=clock, sticky_threshold=2, sticky_window_s=10)
    e = faultinj.DeviceAssertError("x")
    assert hm.record_failure("op", e) == TRANSIENT
    clock.t += 11                      # first failure leaves the window
    assert hm.record_failure("op", e) == TRANSIENT
    clock.t += 1
    assert hm.record_failure("op", e) == STICKY


# ---- backoff + budget -------------------------------------------------------

def test_backoff_exponential_jittered_and_capped():
    hm = _monitor(retry_budget=100, backoff_base_ms=10, backoff_max_ms=200)
    for attempt, lo_hi in enumerate([(5, 10), (10, 20), (20, 40), (40, 80),
                                     (80, 160), (100, 200), (100, 200)]):
        ms = hm.try_retry(attempt)
        lo, hi = lo_hi
        assert lo <= ms <= hi, (attempt, ms)
    # the sleeps actually happened (injected recorder, seconds)
    assert len(hm._test_sleeps) == 7
    assert all(s >= 0.005 for s in hm._test_sleeps)


def test_retry_budget_shared_and_refilled_per_attempt():
    hm = _monitor(retry_budget=3, backoff_base_ms=1)
    assert all(hm.try_retry(0) is not None for _ in range(3))
    assert hm.try_retry(0) is None            # exhausted: caller escalates
    hm.start_plan_attempt()                   # new plan attempt refills
    assert hm.try_retry(0) is not None
    drained = hm.get_and_reset_metrics()
    assert drained["budget_exhausted"] == 1
    assert drained["retries"] == 4


# ---- breaker state machine --------------------------------------------------

def test_breaker_lifecycle():
    ok = {"v": True}
    br = CircuitBreaker(probe=lambda: ok["v"])
    assert br.state == CLOSED and br.admit()
    br.trip("sticky")
    assert br.state == OPEN and not br.admit()
    assert br.trips == 1 and br.last_trip_reason == "sticky"
    br.half_open()
    assert br.state == HALF_OPEN
    ok["v"] = False
    assert not br.admit()                     # failed probe re-opens
    assert br.state == OPEN
    br.half_open()
    ok["v"] = True
    assert br.admit()                         # probe success closes
    assert br.state == CLOSED


def test_breaker_cooldown_self_arms_half_open():
    """Quarantine is never permanent: once cooldown_s elapses, admit()
    probes; a failed probe re-opens AND restarts the cooldown clock."""
    clock = _FakeClock()
    ok = {"v": False}
    br = CircuitBreaker(probe=lambda: ok["v"], cooldown_s=30, clock=clock)
    br.trip("sticky")
    assert not br.admit()                     # still cooling down
    clock.t += 31
    assert not br.admit()                     # probe ran, failed -> OPEN
    assert br.state == OPEN
    clock.t += 10
    assert not br.admit()                     # cooldown restarted at fail
    ok["v"] = True
    clock.t += 31
    assert br.admit()                         # cooldown -> probe -> CLOSED
    assert br.state == CLOSED


def test_breaker_cooldown_zero_disables_self_arm():
    clock = _FakeClock()
    br = CircuitBreaker(probe=lambda: True, cooldown_s=0, clock=clock)
    br.trip("fatal")
    clock.t += 1e9
    assert not br.admit()                     # only reset_device() re-arms
    br.half_open()
    assert br.admit()


def test_retry_budget_is_per_thread():
    """Concurrent plans on a shared monitor get independent budgets: one
    thread's refill or exhaustion must not leak into another's bound."""
    import threading
    hm = _monitor(retry_budget=2, backoff_base_ms=1)
    hm.start_plan_attempt()
    assert hm.try_retry(0) is not None and hm.try_retry(0) is not None
    assert hm.try_retry(0) is None            # this thread: exhausted
    got = {}

    def other():
        hm.start_plan_attempt()               # refills ONLY its thread
        got["ok"] = hm.try_retry(0) is not None

    t = threading.Thread(target=other)
    t.start(); t.join()
    assert got["ok"]                          # fresh budget over there
    assert hm.try_retry(0) is None            # still exhausted here


def test_retry_budget_keyed_by_session_not_thread():
    """Serving-layer aliasing regression (docs/serving.md): one worker
    thread multiplexed across two tenants must give each its own retry
    budget — before session keying, tenant B inherited whatever tenant A
    left of the THREAD's budget."""
    from spark_rapids_tpu.runtime import sessionctx
    hm = _monitor(retry_budget=2, backoff_base_ms=1)
    with sessionctx.session_scope("tenant-a"):
        hm.start_plan_attempt()
        assert hm.try_retry(0) is not None and hm.try_retry(0) is not None
        assert hm.try_retry(0) is None        # tenant A: exhausted
    with sessionctx.session_scope("tenant-b"):
        hm.start_plan_attempt()
        # same thread, different tenant: fresh bound, NOT A's residue
        assert hm.try_retry(0) is not None
    with sessionctx.session_scope("tenant-a"):
        # and B's refill must not have resurrected A's budget
        assert hm.try_retry(0) is None


def test_same_tenant_concurrent_plans_keep_independent_budgets():
    """ONE tenant with two in-flight plans on different workers (the
    normal serving shape): each plan attempt keeps its OWN bounded
    budget — plan 2's start_plan_attempt must not refill plan 1's bound
    mid-plan, and plan 1's retries must not starve plan 2's first."""
    import threading
    from spark_rapids_tpu.runtime import sessionctx
    hm = _monitor(retry_budget=2, backoff_base_ms=1)
    with sessionctx.session_scope("tenant-a"):
        hm.start_plan_attempt()
        assert hm.try_retry(0) is not None and hm.try_retry(0) is not None
        assert hm.try_retry(0) is None        # this plan: exhausted
    got = {}

    def worker2():
        with sessionctx.session_scope("tenant-a"):
            hm.start_plan_attempt()           # its own plan attempt
            got["fresh"] = hm.try_retry(0) is not None

    t = threading.Thread(target=worker2)
    t.start(); t.join()
    assert got["fresh"]                       # independently bounded...
    with sessionctx.session_scope("tenant-a"):
        # ...and worker 2's refill did not resurrect THIS plan's budget
        assert hm.try_retry(0) is None


def test_sticky_windows_keyed_by_session():
    """Tenant A's repeated failures of an op must not arm a sticky trip
    against tenant B's FIRST failure of the same op."""
    from spark_rapids_tpu.runtime import sessionctx
    clock = _FakeClock()
    hm = _monitor(clock=clock, sticky_threshold=2, sticky_window_s=60)
    e = faultinj.DeviceAssertError("x")
    with sessionctx.session_scope("tenant-a"):
        assert hm.record_failure("HashJoin#1", e) == TRANSIENT
    with sessionctx.session_scope("tenant-b"):
        # B's first failure of this op: transient, whatever A did
        assert hm.record_failure("HashJoin#1", e) == TRANSIENT
    with sessionctx.session_scope("tenant-a"):
        assert hm.record_failure("HashJoin#1", e) == STICKY


def test_breaker_probe_exception_counts_as_failure():
    def boom():
        raise faultinj.DeviceFatalError("still dead")
    br = CircuitBreaker(probe=boom)
    br.trip("fatal")
    br.half_open()
    assert not br.probe()
    assert br.state == OPEN


def test_device_probe_runs_tiny_device_op():
    assert device_probe()                     # no injector installed


def test_device_probe_fails_on_poisoned_device(tmp_path, _clean_faultinj):
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "probe-arm": {"percent": 100, "injectionType": 0}}}))
    with pytest.raises(faultinj.DeviceFatalError):
        faultinj.active().on_compute("probe-arm")  # poison via a fatal fault
    br = CircuitBreaker()
    br.trip("fatal")
    br.half_open()
    assert not br.probe()                     # poisoned device refuses
    faultinj.active().reset_device()
    br.half_open()
    assert br.probe()
    assert br.state == CLOSED


# ---- metrics drain ----------------------------------------------------------

def test_metrics_get_and_reset():
    hm = _monitor(backoff_base_ms=1)
    hm.record_failure("op", faultinj.DeviceAssertError("x"))
    hm.try_retry(0)
    hm.trip("sticky")
    hm.note_degraded_plan()
    first = hm.get_and_reset_metrics()
    assert first["transient_faults"] == 1
    assert first["retries"] == 1 and first["backoff_ms"] > 0
    assert first["trips"] == 1 and first["sticky_trips"] == 1
    assert first["degraded_plans"] == 1
    assert hm.get_and_reset_metrics() == {}   # drained


def test_reset_device_clears_poison_and_runs_hooks(tmp_path, _clean_faultinj):
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "arm": {"percent": 100, "injectionType": 0}}}))
    with pytest.raises(faultinj.DeviceFatalError):
        faultinj.active().on_compute("arm")
    assert faultinj.active().device_poisoned
    hm = _monitor()
    e = faultinj.DeviceAssertError("x")
    hm.record_failure("HashJoin#1", e)
    hm.record_failure("HashJoin#1", e)
    hm.trip("fatal")
    ran = []
    hm.add_reset_hook(lambda: ran.append(True))
    hm.reset_device()
    assert not faultinj.active().device_poisoned
    assert ran == [True]
    assert hm.breaker.state == HALF_OPEN
    # stickiness windows restart at the reset: pre-recovery failures must
    # not re-trip the breaker on the first post-recovery transient
    assert hm.record_failure("HashJoin#1", e) == TRANSIENT


# ---- end to end: fatal mid-plan → degraded → reset → half-open → closed -----

def test_fatal_mid_plan_degrades_then_recovers(tmp_path, _clean_faultinj):
    sales, dims = _tables()
    plan = _plan()
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    ref_dict = ref.table.to_pydict()
    assert not ref.degraded and ref.breaker["state"] == "closed"

    # fatal fault at the Sort — everything upstream has already executed
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.Sort": {"percent": 100, "injectionType": 0,
                      "interceptionCount": 1}}}))
    ex = PlanExecutor()
    res = ex.execute(plan, {"sales": sales, "dims": dims})
    assert res.degraded
    assert res.breaker["state"] == "open" and res.breaker["trips"] == 1
    assert res.breaker["reason"] == "fatal"
    assert "DeviceFatalError" in res.breaker["error"]  # the actual culprit
    assert res.table.to_pydict() == ref_dict          # parity via CPU tier
    by_kind = {m.kind: m for m in res.metrics.values()}
    assert by_kind["Sort"].degraded                   # re-ran on the CPU tier
    assert not by_kind["HashJoin"].degraded           # completed pre-trip
    assert by_kind["Sort"].retries == 0               # fatal: never retried
    health = ex.health.get_and_reset_metrics()
    assert health["fatal_faults"] == 1 and health["fatal_trips"] == 1
    assert health["degraded_plans"] == 1

    # breaker open: the device is quarantined, plans run fully degraded
    res2 = ex.execute(plan, {"sales": sales, "dims": dims})
    assert res2.degraded
    assert all(m.degraded for m in res2.metrics.values())
    assert res2.table.to_pydict() == ref_dict

    # operator intervention: reset_device() arms the half-open probation
    # and the heartbeat probe closes the breaker on the next execute
    ex.health.reset_device()
    assert not faultinj.active().device_poisoned
    assert ex.health.breaker.state == HALF_OPEN
    res3 = ex.execute(plan, {"sales": sales, "dims": dims})
    assert not res3.degraded
    assert res3.breaker["state"] == "closed"
    assert res3.table.to_pydict() == ref_dict
    health = ex.health.get_and_reset_metrics()
    assert health["probes"] == 1 and "probe_failures" not in health


def test_sticky_storm_trips_and_degrades(tmp_path, _clean_faultinj):
    sales, dims = _tables()
    plan = _plan()
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.HashAggregate": {"percent": 100, "injectionType": 1}}}))
    hm = _monitor(backoff_base_ms=1)          # no real sleeping
    ex = PlanExecutor(health=hm)
    res = ex.execute(plan, {"sales": sales, "dims": dims})
    assert res.degraded and res.breaker["reason"] == "sticky"
    assert res.table.to_pydict() == ref.table.to_pydict()
    agg = next(m for m in res.metrics.values() if m.kind == "HashAggregate")
    assert agg.retries == 2 and agg.degraded  # bounded retry, then degrade
    assert res.backoff_ms > 0


def test_retry_budget_exhaustion_degrades(tmp_path, _clean_faultinj):
    """A whole-plan fault storm burns the shared budget, not per-op counts:
    with a budget of 1, the second failing operator may not retry at all."""
    sales, dims = _tables()
    plan = _plan()
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.Project": {"percent": 100, "injectionType": 1},
        "plan.Sort": {"percent": 100, "injectionType": 1}}}))
    hm = _monitor(backoff_base_ms=1, retry_budget=1, sticky_threshold=99)
    ex = PlanExecutor(health=hm)
    res = ex.execute(plan, {"sales": sales, "dims": dims})
    assert res.degraded and res.breaker["reason"] == "sticky"
    assert res.table.to_pydict() == ref.table.to_pydict()
    drained = hm.get_and_reset_metrics()
    assert drained["budget_exhausted"] >= 1
    assert drained["retries"] == 1            # the budget, not 3 per op


def test_degrade_off_fatal_raises_with_metrics(tmp_path, _clean_faultinj):
    sales, dims = _tables()
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.Sort": {"percent": 100, "injectionType": 0,
                      "interceptionCount": 1}}}))
    with pytest.raises(faultinj.DeviceFatalError) as ei:
        PlanExecutor(degrade="off").execute(
            _plan(), {"sales": sales, "dims": dims})
    done = {m.kind for m in ei.value.plan_metrics.values()}
    assert "HashAggregate" in done and "Sort" not in done


def test_capped_fatal_degrades_with_parity(tmp_path, _clean_faultinj):
    sales, dims = _tables()
    plan = _plan()
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.HashJoin": {"percent": 100, "injectionType": 0,
                          "interceptionCount": 1}}}))
    res = PlanExecutor(mode="capped").execute(
        plan, {"sales": sales, "dims": dims})
    assert res.degraded and res.mode == "capped"
    assert res.breaker["reason"] == "fatal"
    # degraded capped results are unpadded (valid=None): compact() is id
    assert res.compact().to_pydict() == ref.table.to_pydict()


def test_degraded_result_visible_in_profile_text(tmp_path, _clean_faultinj):
    sales, dims = _tables()
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.Sort": {"percent": 100, "injectionType": 0,
                      "interceptionCount": 1}}}))
    res = PlanExecutor().execute(_plan(), {"sales": sales, "dims": dims})
    txt = res.profile_text()
    assert "DEGRADED" in txt and "breaker open (fatal)" in txt


def test_non_degraded_run_has_no_degraded_banner(tmp_path, _clean_faultinj):
    """A device-tier success after an earlier trip (degrade="off" keeps
    executing) must not claim CPU-tier completion in its profile."""
    sales, dims = _tables()
    plan = _plan()
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.Sort": {"percent": 100, "injectionType": 1,
                      "interceptionCount": 3}}}))
    ex = PlanExecutor(op_retries=0, degrade="off",
                      health=_monitor(backoff_base_ms=1))
    with pytest.raises(faultinj.DeviceAssertError):
        ex.execute(plan, {"sales": sales, "dims": dims})
    assert ex.health.breaker.state == OPEN    # tripped, but device-tier
    faultinj.active().compute_rules["plan.Sort"].count = 0  # fault clears
    res = ex.execute(plan, {"sales": sales, "dims": dims})
    assert not res.degraded
    assert "DEGRADED" not in res.profile_text()


def test_degraded_tier_survives_active_session(tmp_path, _clean_faultinj):
    """With a DeviceSession scoped to the execution, the degraded tier
    must still complete: faultinj also shims MemoryBudget.acquire, and a
    poisoned device fail-fasts EVERY intercepted call — the CPU tier
    suppresses interception wholesale (faultinj.suppressed)."""
    from spark_rapids_tpu.runtime import DeviceSession
    sales, dims = _tables(n=500)
    plan = _plan()
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.HashJoin": {"percent": 100, "injectionType": 0,
                          "interceptionCount": 1}}}))
    with DeviceSession(device_limit_bytes=64 * 1024 * 1024,
                       watchdog=False) as session:
        res = PlanExecutor(session=session).execute(
            plan, {"sales": sales, "dims": dims})
    assert res.degraded and res.breaker["reason"] == "fatal"
    assert res.table.to_pydict() == ref.table.to_pydict()


def test_optimized_plan_degrades_with_fused_dag(tmp_path, _clean_faultinj):
    """Optimizer interaction (docs/optimizer.md): an optimized plan that
    trips the breaker mid-run must salvage and finish on the CPU tier with
    the OPTIMIZED DAG — fused nodes are not re-expanded, the degraded tier
    lowers FusedSelect like any other operator."""
    sales, dims = _tables(n=800)
    b = PlanBuilder()
    s = b.scan("sales", schema=["k", "v"])
    d = b.scan("dims", schema=["dk", "grp"])
    # the predicate spans BOTH join sides, so pushdown cannot move it and
    # select_fusion merges Filter+Project into one FusedSelect
    plan = (s.join(d, left_on="k", right_on="dk")
             .filter((col("grp") == 1) & (col("v") > 0))
             .project({"grp": col("grp"), "rev": col("v") * lit(2)})
             .aggregate(["grp"], [("rev", "sum", "total")])
             .sort(["grp"])
             .build())
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    fused_kinds = [m.kind for m in ref.metrics.values()]
    assert "FusedSelect" in fused_kinds          # the rewrite really fired

    # fatal at the Sort: everything upstream (incl. the fused select on the
    # join's build side) already executed and must salvage as-is
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.Sort": {"percent": 100, "injectionType": 0,
                      "interceptionCount": 1}}}))
    res = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    assert res.degraded and res.breaker["reason"] == "fatal"
    assert res.table.to_pydict() == ref.table.to_pydict()
    by_kind = {m.kind: m for m in res.metrics.values()}
    assert "FusedSelect" in by_kind              # optimized DAG, both tiers
    assert not by_kind["FusedSelect"].degraded   # salvaged, not re-run
    assert by_kind["Sort"].degraded              # re-ran on the CPU tier
    assert res.optimizer is not None and res.optimizer["rules_fired"]
    # poisoned device, fresh executor: the FULLY-degraded run also executes
    # the optimized DAG (FusedSelect lowers on the CPU tier, no expansion)
    res2 = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    assert res2.degraded
    fused2 = next(m for m in res2.metrics.values()
                  if m.kind == "FusedSelect")
    assert fused2.degraded
    assert res2.table.to_pydict() == ref.table.to_pydict()


def test_capped_degrade_preserves_retry_accounting(tmp_path, _clean_faultinj):
    """Retries/backoff absorbed on the device path before a capped-tier
    trip must survive into the degraded PlanResult."""
    sales, dims = _tables()
    plan = _plan()
    ref = PlanExecutor().execute(plan, {"sales": sales, "dims": dims})
    faultinj.install(_write_cfg(tmp_path, {"computeFaults": {
        "plan.HashAggregate": {"percent": 100, "injectionType": 1}}}))
    hm = _monitor(backoff_base_ms=1)
    res = PlanExecutor(mode="capped", health=hm).execute(
        plan, {"sales": sales, "dims": dims})
    assert res.degraded and res.mode == "capped"
    assert res.retries == 2 and res.backoff_ms > 0
    assert res.compact().to_pydict() == ref.table.to_pydict()
