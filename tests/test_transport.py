"""Exchange transport layer (plan/transport.py, docs/distributed.md
#transport): pack/unpack round-trip parity over the dtype x validity
matrix, codec selection vs strict pass-through, and the byte-accounting
invariants (wire <= logical, pass-through == identical layout). The
end-to-end distributed wiring is covered in tests/test_plan_distributed.py;
this file pins the codec layer itself."""
import numpy as np
import pytest

import jax.numpy as jnp

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import dtypes
from spark_rapids_tpu.columnar import Column
from spark_rapids_tpu.plan import transport

ALL = transport.ALL_CODECS

_DTYPES = {
    "i8": (dtypes.INT8, np.int8),
    "i16": (dtypes.INT16, np.int16),
    "i32": (dtypes.INT32, np.int32),
    "i64": (dtypes.INT64, np.int64),
    "f32": (dtypes.FLOAT32, np.float32),
    "bool": (dtypes.BOOL, np.bool_),
}


def _col(tag, n, validity_mode, seed=0):
    dt, np_dt = _DTYPES[tag]
    rng = np.random.default_rng(seed)
    if tag == "bool":
        data = rng.integers(0, 2, n).astype(np_dt)
    elif tag == "f32":
        data = rng.standard_normal(n).astype(np_dt)
    else:
        info = np.iinfo(np_dt)
        data = rng.integers(max(info.min, -1000),
                            min(info.max, 1000), n).astype(np_dt)
    if validity_mode == "none":
        validity = None
    elif validity_mode == "all_null":
        validity = np.zeros(n, bool)
    else:
        validity = rng.integers(0, 2, n).astype(bool)
    return Column(dtype=dt, length=n, data=jnp.asarray(data),
                  validity=None if validity is None
                  else jnp.asarray(validity))


def _assert_col_roundtrip(src: Column, out: Column, live=None):
    """Live valid slots must round-trip exactly; null/dead slot data is
    sentinel garbage no consumer reads."""
    assert out.dtype == src.dtype and out.length == src.length
    mask = np.ones(src.length, bool) if live is None else np.asarray(live)
    if src.validity is None:
        assert out.validity is None or bool(np.asarray(out.validity)[mask].all())
    else:
        np.testing.assert_array_equal(np.asarray(out.validity)[mask],
                                      np.asarray(src.validity)[mask])
        mask = mask & np.asarray(src.validity)
    np.testing.assert_array_equal(np.asarray(out.data)[mask],
                                  np.asarray(src.data)[mask])


@pytest.mark.parametrize("tag", sorted(_DTYPES))
@pytest.mark.parametrize("validity_mode", ["none", "all_null", "mixed"])
@pytest.mark.parametrize("n", [0, 1, 257])
def test_device_pack_roundtrip_matrix(tag, validity_mode, n):
    cols = [_col(tag, n, validity_mode, seed=n + 1),
            _col("i64", n, "mixed", seed=7),
            _col(tag, n, validity_mode, seed=n + 3)]
    names = ["a", "b", "c"]
    live = jnp.asarray(np.arange(n) % 3 != 0) if n else \
        jnp.zeros((0,), bool)
    dp = transport.pack_device(cols, names, live, ALL)
    assert dp.wire_row_bytes <= dp.logical_row_bytes
    out = transport.unpack_device(dp.planes, dp)
    for src, dst in zip(cols, out):
        _assert_col_roundtrip(src, dst, live=live)
    # numpy mirror (the packed gather's decode) agrees
    nps = transport.unpack_device_np([np.asarray(p) for p in dp.planes], dp)
    for src, (data, validity) in zip(cols, nps):
        dst = Column(dtype=src.dtype, length=n, data=jnp.asarray(data),
                     validity=None if validity is None
                     else jnp.asarray(validity))
        _assert_col_roundtrip(src, dst, live=live)


def test_device_for_narrowing_and_passthrough():
    n = 512
    narrow = Column(dtype=dtypes.INT64, length=n,
                    data=jnp.asarray(np.arange(n, dtype=np.int64) % 200
                                     + 10_000))
    wide = Column(dtype=dtypes.INT64, length=n,
                  data=jnp.asarray(
                      np.linspace(-2**62, 2**62, n).astype(np.int64)))
    live = jnp.ones((n,), bool)
    dp = transport.pack_device([narrow, wide], ["nar", "wid"], live, ALL)
    # narrow-range int64 -> uint8 FOR plane; full-range stays raw
    assert "nar:for8" in dp.codec_str and "wid" not in dp.codec_str
    assert dp.wire_row_bytes == 1 + 8
    assert dp.logical_row_bytes == 8 + 8
    out = transport.unpack_device(dp.planes, dp)
    for src, dst in zip([narrow, wide], out):
        _assert_col_roundtrip(src, dst)
    # dead rows are excluded from the FOR range probe: a column whose
    # LIVE prefix is narrow narrows even when dead slots carry garbage
    mixed = Column(dtype=dtypes.INT64, length=n, data=jnp.asarray(
        np.where(np.arange(n) < 8, np.arange(n), 2**62).astype(np.int64)))
    live2 = jnp.asarray(np.arange(n) < 8)
    dp2 = transport.pack_device([mixed], ["mix"], live2, ALL)
    assert "mix:for8" in dp2.codec_str
    (dec,) = transport.unpack_device(dp2.planes, dp2)
    _assert_col_roundtrip(mixed, dec, live=live2)


def test_device_validity_bitpack_collapses_planes():
    n = 64
    cols = [_col("i32", n, "mixed", seed=i) for i in range(5)]
    names = [f"c{i}" for i in range(5)]
    live = jnp.ones((n,), bool)
    dp = transport.pack_device(cols, names, live, ALL)
    assert "validity:bitpack" in dp.codec_str
    # 5 bool planes (5 B/row) collapse into one bit-word plane (1 B/row)
    assert dp.wire_row_bytes <= dp.logical_row_bytes - 4
    for src, dst in zip(cols, transport.unpack_device(dp.planes, dp)):
        _assert_col_roundtrip(src, dst)
    # codecs "none": layout-only pass-through, wire == logical
    dp_raw = transport.pack_device(cols, names, live, frozenset())
    assert dp_raw.codec_str == ""
    assert dp_raw.wire_row_bytes == dp_raw.logical_row_bytes


@pytest.mark.parametrize("shape", ["sorted", "lowcard", "narrow", "wide",
                                   "empty"])
def test_host_codec_selection_and_roundtrip(shape):
    n = 0 if shape == "empty" else 1000
    rng = np.random.default_rng(11)
    if shape == "sorted":
        a = np.sort(rng.integers(0, 40, n)).astype(np.int64)
        want = "rle"
    elif shape == "lowcard":
        a = rng.integers(0, 7, n).astype(np.int64) * 10**12
        want = "dict8"
    elif shape == "narrow":
        a = rng.integers(0, 60_000, n).astype(np.int64)
        want = "for16"
    else:
        a = rng.integers(-2**62, 2**62, n).astype(np.int64)
        want = "raw"
    validity = rng.integers(0, 2, n).astype(bool) if n else None
    col = Column(dtype=dtypes.INT64, length=n, data=jnp.asarray(a),
                 validity=None if validity is None
                 else jnp.asarray(validity))
    hp = transport.pack_host([col], ["x"], ALL)
    got = dict(p.split(":") for p in hp.codec_str.split(",")
               if p and ":" in p).get("x", "raw")
    assert got == want, (shape, hp.codec_str)
    assert hp.wire_bytes <= hp.logical_bytes
    (out,) = transport.unpack_host(hp)
    # host codecs are lossless for EVERY slot (null data included)
    np.testing.assert_array_equal(np.asarray(out.data), a)
    if validity is not None:
        np.testing.assert_array_equal(np.asarray(out.validity), validity)
    # device decode mirror (the broadcast receiving shard)
    (dev,) = transport.unpack_host_device(hp, lambda x: x)
    np.testing.assert_array_equal(np.asarray(dev.data), a)


def test_host_float_and_bool_pass_through():
    n = 100
    rng = np.random.default_rng(3)
    f = Column(dtype=dtypes.FLOAT64, length=n,
               data=jnp.asarray(rng.standard_normal(n)))
    bcol = Column(dtype=dtypes.BOOL, length=n,
                  data=jnp.asarray(rng.integers(0, 2, n).astype(bool)))
    hp = transport.pack_host([f, bcol], ["f", "b"], ALL)
    assert "f:" not in hp.codec_str and "b:" not in hp.codec_str
    outs = transport.unpack_host(hp)
    np.testing.assert_array_equal(np.asarray(outs[0].data),
                                  np.asarray(f.data))
    np.testing.assert_array_equal(np.asarray(outs[1].data),
                                  np.asarray(bcol.data))


def test_bitmask_roundtrip():
    for n in (0, 1, 7, 8, 9, 257):
        mask = np.arange(n) % 5 != 0
        plane, m = transport.pack_bits_device(jnp.asarray(mask))
        assert m == n and np.asarray(plane).nbytes == (n + 7) // 8
        np.testing.assert_array_equal(
            transport.unpack_bits_np(np.asarray(plane), n), mask)


def test_resolve_codecs_strict():
    assert transport.resolve_codecs("auto") == ALL
    assert transport.resolve_codecs("none") == frozenset()
    assert transport.resolve_codecs("for,bitpack") == \
        frozenset({"for", "bitpack"})
    with pytest.raises(ValueError, match="unknown exchange codec"):
        transport.resolve_codecs("zstd")
