"""Bloom filter tests against a pure-Python Spark BloomFilterImpl oracle
(same role as the reference's BloomFilterTest.java:42-185, which probes
GPU-built filters against Spark-serialized buffers)."""
import numpy as np
import pytest

from spark_rapids_tpu import dtypes
from spark_rapids_tpu.columnar import Column
from spark_rapids_tpu.ops.bloom_filter import (
    bloom_filter_create, bloom_filter_put, bloom_filter_merge,
    bloom_filter_probe, bloom_filter_serialize, bloom_filter_deserialize)

from spark_hash_oracle import murmur32_bytes, encode_int8


def _to_i32(v):
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


class SparkBloomOracle:
    """Pure-Python BloomFilterImpl: BitArray of longs + double hashing."""

    def __init__(self, num_hashes, num_longs):
        self.k = num_hashes
        self.longs = [0] * num_longs
        self.num_bits = num_longs * 64

    def _indexes(self, item):
        h1 = murmur32_bytes(encode_int8(item), 0)
        h2 = murmur32_bytes(encode_int8(item), h1 & 0xFFFFFFFF)
        out = []
        for i in range(1, self.k + 1):
            combined = _to_i32(h1 + i * h2)
            if combined < 0:
                combined = ~combined
            out.append(combined % self.num_bits)
        return out

    def put(self, item):
        for idx in self._indexes(item):
            self.longs[idx >> 6] |= (1 << (idx & 63))

    def might_contain(self, item):
        return all(self.longs[i >> 6] & (1 << (i & 63)) for i in self._indexes(item))

    def serialize(self) -> bytes:
        out = (1).to_bytes(4, "big") + self.k.to_bytes(4, "big") + \
            len(self.longs).to_bytes(4, "big")
        for v in self.longs:
            out += (v & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big")
        return out


def _col(vals):
    return Column.from_pylist(vals, dtypes.INT64)


def test_wire_format_matches_spark():
    rng = np.random.default_rng(0)
    vals = [int(v) for v in rng.integers(-(2**62), 2**62, size=200)]
    oracle = SparkBloomOracle(3, 8)
    for v in vals:
        oracle.put(v)
    bf = bloom_filter_put(bloom_filter_create(3, 8), _col(vals))
    got = bytes(np.asarray(bloom_filter_serialize(bf)))
    assert got == oracle.serialize()


def test_probe_matches_oracle():
    rng = np.random.default_rng(1)
    put_vals = [int(v) for v in rng.integers(-(2**40), 2**40, size=500)]
    probe_vals = put_vals[:100] + [int(v) for v in rng.integers(2**41, 2**42, size=200)]
    oracle = SparkBloomOracle(5, 64)
    for v in put_vals:
        oracle.put(v)
    bf = bloom_filter_put(bloom_filter_create(5, 64), _col(put_vals))
    got = bloom_filter_probe(_col(probe_vals), bf).to_pylist()
    want = [oracle.might_contain(v) for v in probe_vals]
    assert got == want
    assert all(got[:100])  # no false negatives ever


def test_deserialize_spark_buffer_and_probe():
    oracle = SparkBloomOracle(4, 16)
    for v in [1, 2, 3, 1000, -5_000_000_000]:
        oracle.put(v)
    bf = bloom_filter_deserialize(np.frombuffer(oracle.serialize(), np.uint8))
    assert bf.num_hashes == 4 and bf.num_longs == 16
    got = bloom_filter_probe(_col([1, 2, 3, 1000, -5_000_000_000, 77]), bf).to_pylist()
    assert got[:5] == [True] * 5
    assert got[5] == oracle.might_contain(77)


def test_serialize_roundtrip():
    bf = bloom_filter_put(bloom_filter_create(2, 4), _col([10, 20, 30]))
    rt = bloom_filter_deserialize(np.asarray(bloom_filter_serialize(bf)))
    assert np.array_equal(np.asarray(rt.bits), np.asarray(bf.bits))


def test_merge():
    a = bloom_filter_put(bloom_filter_create(3, 8), _col([1, 2, 3]))
    b = bloom_filter_put(bloom_filter_create(3, 8), _col([100, 200]))
    m = bloom_filter_merge([a, b])
    got = bloom_filter_probe(_col([1, 2, 3, 100, 200]), m).to_pylist()
    assert got == [True] * 5
    with pytest.raises(ValueError):
        bloom_filter_merge([a, bloom_filter_create(2, 8)])
    with pytest.raises(ValueError):
        bloom_filter_merge([a, bloom_filter_create(3, 4)])


def test_nulls_skipped_on_put_pass_through_on_probe():
    bf = bloom_filter_put(bloom_filter_create(3, 8), _col([1, None, 3]))
    oracle = SparkBloomOracle(3, 8)
    oracle.put(1)
    oracle.put(3)
    assert bytes(np.asarray(bloom_filter_serialize(bf))) == oracle.serialize()
    got = bloom_filter_probe(_col([1, None]), bf).to_pylist()
    assert got == [True, None]


def test_deserialize_validation():
    with pytest.raises(ValueError):
        bloom_filter_deserialize(np.zeros(4, np.uint8))
    bad_version = (9).to_bytes(4, "big") + (1).to_bytes(4, "big") + \
        (1).to_bytes(4, "big") + b"\x00" * 8
    with pytest.raises(ValueError):
        bloom_filter_deserialize(np.frombuffer(bad_version, np.uint8))


def test_put_sort_indices_variant_matches():
    import numpy as np
    rng = np.random.default_rng(5)
    vals = Column.from_pylist(
        [int(v) for v in rng.integers(-2**62, 2**62, 500)] + [None],
        dtypes.INT64)
    bf = bloom_filter_create(3, 1024)
    a = bloom_filter_put(bf, vals)
    b = bloom_filter_put(bf, vals, sort_indices=True)
    np.testing.assert_array_equal(np.asarray(a.bits), np.asarray(b.bits))
