"""Concurrency audit: faultinj install/uninstall + StatsStore recording
under concurrent in-flight plans (ISSUE 15 satellite — the PR 12 locks
existed but were never exercised by >1 plan at once).

What the stress threads actually race:

- the fault injector's interception surface (rule draw, the injected
  counter, poisoned-device flag) against 8 threads of plan executions
  AND a flapping install()/uninstall() cycle on a 9th;
- a single shared StatsStore receiving record_result from every thread
  (generation monotonicity, table integrity, JSONL append atomicity);
- one shared PlanExecutor's LruDict-backed memo caches (rewrite,
  verify, cert, compiled-program) — the pop/reinsert recency dance is
  the classic lost-update window.

Assertions are invariants, not schedules: no unexpected exception, exact
record/generation accounting, per-line-valid JSONL, the ops surface
restored shim-free after the final uninstall.
"""
import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import Column, Table, dtypes, faultinj
from spark_rapids_tpu.plan import PlanBuilder, PlanExecutor, col
from spark_rapids_tpu.plan import stats as stats_mod
from spark_rapids_tpu.utils.lru import LruDict


def _col(a):
    a = np.asarray(a, dtype=np.int64)
    return Column(dtype=dtypes.INT64, length=len(a), data=jnp.asarray(a))


def _table(n, seed):
    rng = np.random.default_rng(seed)
    return Table([_col(rng.integers(0, 40, n)),
                  _col(rng.integers(1, 100, n))], names=["k", "v"])


def _plan():
    b = PlanBuilder()
    return (b.scan("t", schema=["k", "v"]).filter(col("v") > 5)
            .aggregate(["k"], [("v", "sum", "total"),
                               ("v", "max", "peak")])
            .sort(["k"]).build())


@pytest.fixture
def _clean_faultinj():
    yield
    faultinj.uninstall()


def test_lru_dict_concurrent_get_never_drops_entries():
    """The recency refresh (pop + reinsert) under concurrent get():
    before the internal lock, two threads hitting one key raced the pop
    and the loser raised KeyError (or the entry vanished)."""
    d = LruDict(64)
    for i in range(32):
        d[i] = i * 10
    errors = []

    def hammer(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(4000):
                k = int(rng.integers(0, 32))
                v = d.get(k)
                assert v is None or v == k * 10
                if rng.integers(0, 4) == 0:
                    d[k] = k * 10
        except Exception as e:          # pragma: no cover - the regression
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(d) <= 64
    for i in range(32):
        assert d.get(i) == i * 10       # nothing was silently dropped


def test_concurrent_sessions_stats_store_consistency(tmp_path):
    """8 threads, one shared executor + one shared StatsStore: every
    successful execution records exactly once (generation == records),
    the persisted JSONL has one valid line per record (append
    atomicity), and results stay bit-exact per thread."""
    plan = _plan()
    tables = {i: _table(600 + 8 * i, seed=i) for i in range(8)}
    solo = PlanExecutor(mode="eager", optimize=True)
    refs = {i: solo.execute(plan, {"t": t}).table.to_pydict()
            for i, t in tables.items()}
    path = str(tmp_path / "stats.jsonl")
    store = stats_mod.StatsStore(capacity=64, path=path)
    ex = PlanExecutor(mode="eager")
    runs_per_thread = 6
    errors = []

    def worker(i):
        try:
            with stats_mod.scoped_store(store):
                for _ in range(runs_per_thread):
                    res = ex.execute(plan, {"t": tables[i]})
                    assert res.table.to_pydict() == refs[i]
        except Exception as e:
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert store.generation == 8 * runs_per_thread
    with open(path) as f:
        lines = f.readlines()
    assert len(lines) == 8 * runs_per_thread
    for line in lines:
        ev = json.loads(line)           # no torn/interleaved appends
        assert ev["backend"] == jax.default_backend()


def test_faultinj_flapping_install_under_concurrent_plans(tmp_path,
                                                          _clean_faultinj):
    """install()/uninstall() cycling while 6 threads execute plans: no
    lost originals, no crash beyond the injected taxonomy, and the ops
    surface comes back shim-free after the final uninstall."""
    cfg = tmp_path / "inj.json"
    cfg.write_text(json.dumps({"seed": 7, "computeFaults": {
        "plan.HashAggregate": {"percent": 20, "injectionType": 1,
                               "interceptionCount": 100000}}}))
    plan = _plan()
    tables = {i: _table(500, seed=100 + i) for i in range(6)}
    solo = PlanExecutor(mode="eager")
    refs = {i: solo.execute(plan, {"t": t}).table.to_pydict()
            for i, t in tables.items()}
    ex = PlanExecutor(mode="eager")
    ex.health.backoff_base_ms = 0.01
    ex.health.backoff_max_ms = 0.05
    stop = threading.Event()
    errors = []

    def flapper():
        try:
            while not stop.is_set():
                faultinj.install(str(cfg))
                faultinj.uninstall()
        except Exception as e:
            errors.append(("flapper", e))

    def worker(i):
        try:
            for _ in range(8):
                res = ex.execute(plan, {"t": tables[i]})
                assert res.table.to_pydict() == refs[i]
        except Exception as e:
            errors.append((i, e))

    fl = threading.Thread(target=flapper)
    ths = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    fl.start()
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    stop.set()
    fl.join()
    assert not errors, errors
    faultinj.uninstall()
    from spark_rapids_tpu import ops
    for name in ops.__all__:
        fn = getattr(ops, name)
        assert not hasattr(fn, "__faultinj_shim__"), \
            f"uninstall left a live shim on ops.{name}"
    assert faultinj.active() is None


def test_fatal_poison_flag_is_atomic_under_contention(tmp_path,
                                                      _clean_faultinj):
    """A fatal injection and a racing reset_device() leave the injector
    in a coherent state: the fatal either poisons (later calls refuse)
    or the reset lands after it — never a counted-but-unpoisoned tear."""
    cfg = tmp_path / "fatal.json"
    cfg.write_text(json.dumps({"seed": 1, "computeFaults": {
        "boom": {"percent": 100, "injectionType": 0,
                 "interceptionCount": 1000000}}}))
    inj = faultinj.install(str(cfg))
    hits = {"fatal": 0}
    lock = threading.Lock()

    def attacker():
        for _ in range(300):
            try:
                inj.on_compute("boom")
            except faultinj.DeviceFatalError:
                with lock:
                    hits["fatal"] += 1

    def resetter():
        for _ in range(300):
            inj.reset_device()

    ths = [threading.Thread(target=attacker) for _ in range(4)] + \
        [threading.Thread(target=resetter)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert hits["fatal"] > 0
    # every COUNTED injection raised (poisoned-device refusals raise the
    # same error without counting, so injected <= raised) and the racing
    # resets never tore the counter to zero
    drained = inj.get_and_reset_injected()
    assert 0 < drained <= hits["fatal"]
    inj.reset_device()
    hits2 = 0
    try:
        inj.on_compute("health.probe")   # unmatched key: no rule fires
    except faultinj.DeviceFatalError:    # pragma: no cover
        hits2 = 1
    assert hits2 == 0
