"""Scatter-free bucket partitioning (round-2 mandate #4): the scan path and
the Pallas histogram kernel agree with the sort-based build_partition_map
and with numpy oracles, including skew, empty buckets and overflow."""
import numpy as np
import pytest

import jax.numpy as jnp

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.parallel.partition import (build_partition_map_scan,
                                                 partition_histogram,
                                                 partition_ranks)
from spark_rapids_tpu.parallel.partition_pallas import histogram_pallas
from spark_rapids_tpu.parallel.shuffle import build_partition_map


@pytest.mark.parametrize("n,P", [(1, 1), (257, 4), (10_000, 16), (4096, 128)])
def test_histograms_match_bincount(n, P):
    rng = np.random.default_rng(n)
    part = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
    ref = np.bincount(np.asarray(part), minlength=P)
    np.testing.assert_array_equal(np.asarray(partition_histogram(part, P)), ref)
    np.testing.assert_array_equal(np.asarray(histogram_pallas(part, P)), ref)


def test_histogram_skewed_and_empty_buckets():
    part = jnp.asarray(np.zeros(5000, np.int32))      # all one bucket
    got = np.asarray(partition_histogram(part, 8))
    assert got[0] == 5000 and got[1:].sum() == 0
    got_p = np.asarray(histogram_pallas(part, 8))
    np.testing.assert_array_equal(got_p, got)


def test_ranks_are_stable_slots():
    rng = np.random.default_rng(7)
    n, P = 3000, 5
    part_np = rng.integers(0, P, n).astype(np.int32)
    ranks, counts = partition_ranks(jnp.asarray(part_np), P)
    r = np.asarray(ranks)
    seen = np.zeros(P, np.int64)
    for i in range(n):
        assert r[i] == seen[part_np[i]]
        seen[part_np[i]] += 1
    np.testing.assert_array_equal(np.asarray(counts), seen)


def test_ranks_cross_block_boundaries():
    # rows of one bucket spanning several scan blocks keep a global rank
    n = 5000
    part = jnp.asarray(np.zeros(n, np.int32))
    ranks, counts = partition_ranks(part, 2, block_rows=512)
    np.testing.assert_array_equal(np.asarray(ranks), np.arange(n))
    assert int(counts[0]) == n


@pytest.mark.parametrize("cap_factor", [2.0, 0.5])
def test_partition_map_scan_matches_sort_path(cap_factor):
    rng = np.random.default_rng(3)
    n, P = 20_000, 16
    cap = int(n / P * cap_factor)
    part = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
    g1, v1, c1 = build_partition_map(part, P, cap)
    g2, v2, c2 = build_partition_map_scan(part, P, cap)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    # both must place the same rows in the same (bucket, slot) cells
    np.testing.assert_array_equal(np.asarray(g1)[np.asarray(v1)],
                                  np.asarray(g2)[np.asarray(v2)])
    if cap_factor < 1.0:
        assert bool((np.asarray(c2) > cap).any())     # overflow reported


def test_pallas_bucket_cap():
    with pytest.raises(ValueError):
        histogram_pallas(jnp.zeros(8, jnp.int32), 129)
