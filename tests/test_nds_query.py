"""NDS/TPC-DS Q3-shaped end-to-end correctness: the star-join → multi-key
groupby → order-by pipeline — THE SAME `q3` plan the benchmark runs
(imported from benchmarks/bench_nds_q3.py, so bench and test cannot
drift) — against a pandas oracle, chained exactly the way the Spark
plugin's physical plan would drive it (BASELINE.json north star shape)."""
import numpy as np
import pandas as pd

import spark_rapids_tpu  # noqa: F401

from benchmarks.bench_nds_q3 import _datagen, build_tables, q3


def test_nds_q3_pipeline_matches_pandas():
    n_sales = 30_000
    sales, dates, items = build_tables(n_sales, seed=7)
    out = q3(sales, dates, items)

    # pandas oracle, same plan
    (date_sk, d_year, d_moy, item_sk, i_brand, i_manufact, ss) = \
        _datagen(n_sales, seed=7)
    sdf = pd.DataFrame(ss)
    ddf = pd.DataFrame({"d_date_sk": date_sk, "d_year": d_year,
                        "d_moy": d_moy})
    idf = pd.DataFrame({"i_item_sk": item_sk, "i_brand": i_brand,
                        "i_manufact": i_manufact})
    j = (sdf.merge(ddf[ddf.d_moy == 11], left_on="sold_date_sk",
                   right_on="d_date_sk")
            .merge(idf[idf.i_manufact == 42], left_on="item_sk",
                   right_on="i_item_sk"))
    ref = (j.groupby(["d_year", "i_brand"], as_index=False)
            .agg(revenue=("price_cents", "sum"))
            .sort_values(["d_year", "revenue"], ascending=[True, False]))

    got = pd.DataFrame({
        "d_year": out["d_year"].to_pylist(),
        "i_brand": out["i_brand"].to_pylist(),
        "revenue": out["revenue"].to_pylist(),
    })
    assert len(got) == len(ref)
    # ties in revenue may order differently; the presentation sort must hold
    # on (year, revenue) and the full rows must agree as multisets
    np.testing.assert_array_equal(got.d_year.values, ref.d_year.values)
    np.testing.assert_array_equal(got.revenue.values, ref.revenue.values)
    assert (sorted(zip(got.d_year, got.i_brand, got.revenue)) ==
            sorted(zip(ref.d_year, ref.i_brand, ref.revenue)))
