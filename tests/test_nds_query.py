"""NDS/TPC-DS Q3-shaped end-to-end correctness: the star-join → multi-key
groupby → order-by pipeline — THE SAME `q3` plan the benchmark runs
(imported from benchmarks/bench_nds_q3.py, so bench and test cannot
drift) — against a pandas oracle, chained exactly the way the Spark
plugin's physical plan would drive it (BASELINE.json north star shape)."""
import numpy as np
import pandas as pd

import spark_rapids_tpu  # noqa: F401

from benchmarks.bench_nds_q3 import _datagen, build_tables, q3, q3_capped


def test_nds_q3_pipeline_matches_pandas():
    n_sales = 30_000
    sales, dates, items = build_tables(n_sales, seed=7)
    out = q3(sales, dates, items)

    # pandas oracle, same plan
    (date_sk, d_year, d_moy, item_sk, i_brand, i_manufact, ss) = \
        _datagen(n_sales, seed=7)
    sdf = pd.DataFrame(ss)
    ddf = pd.DataFrame({"d_date_sk": date_sk, "d_year": d_year,
                        "d_moy": d_moy})
    idf = pd.DataFrame({"i_item_sk": item_sk, "i_brand": i_brand,
                        "i_manufact": i_manufact})
    j = (sdf.merge(ddf[ddf.d_moy == 11], left_on="sold_date_sk",
                   right_on="d_date_sk")
            .merge(idf[idf.i_manufact == 42], left_on="item_sk",
                   right_on="i_item_sk"))
    ref = (j.groupby(["d_year", "i_brand"], as_index=False)
            .agg(revenue=("price_cents", "sum"))
            .sort_values(["d_year", "revenue"], ascending=[True, False]))

    got = pd.DataFrame({
        "d_year": out["d_year"].to_pylist(),
        "i_brand": out["i_brand"].to_pylist(),
        "revenue": out["revenue"].to_pylist(),
    })
    assert len(got) == len(ref)
    # ties in revenue may order differently; the presentation sort must hold
    # on (year, revenue) and the full rows must agree as multisets
    np.testing.assert_array_equal(got.d_year.values, ref.d_year.values)
    np.testing.assert_array_equal(got.revenue.values, ref.revenue.values)
    assert (sorted(zip(got.d_year, got.i_brand, got.revenue)) ==
            sorted(zip(ref.d_year, ref.i_brand, ref.revenue)))

    # the jitted capped tier (what the bench measures) agrees with the
    # eager plan row for row
    import jax
    capped, valid, overflow = jax.jit(q3_capped)(sales, dates, items)
    assert not bool(overflow)
    m = np.asarray(valid)
    assert m.sum() == len(ref)
    for name in ("d_year", "i_brand", "revenue"):
        np.testing.assert_array_equal(
            np.asarray(capped[name].data)[m],
            np.asarray(out[name].data), err_msg=name)


def test_nds_q5_pipeline_matches_pandas():
    from benchmarks.bench_nds_q5 import (DATE_HI, DATE_LO, _datagen,
                                         build_tables, q5, q5_capped)
    n_sales = 30_000
    tabs, dates = build_tables(n_sales, seed=3)
    out = q5(tabs, dates)

    chans, _ = _datagen(n_sales, seed=3)
    frames = []
    for ci, (name, c) in enumerate(chans.items()):
        s = pd.DataFrame({"sk": c["s_sk"], "date_sk": c["s_date"],
                          "sales": c["s_price"], "profit": c["s_profit"],
                          "returns": 0, "loss": 0})
        r = pd.DataFrame({"sk": c["r_sk"], "date_sk": c["r_date"],
                          "sales": 0, "profit": 0, "returns": c["r_amt"],
                          "loss": c["r_loss"]})
        u = pd.concat([s, r])
        u = u[(u.date_sk >= DATE_LO) & (u.date_sk < DATE_HI)]
        g = (u.groupby("sk", as_index=False)
              .agg(sales=("sales", "sum"), returns=("returns", "sum"),
                   profit=("profit", "sum"), loss=("loss", "sum")))
        g.insert(0, "channel", ci)
        frames.append(g)
    allch = pd.concat(frames)
    sub = (allch.groupby("channel", as_index=False)
                .agg(sales=("sales", "sum"), returns=("returns", "sum"),
                     profit=("profit", "sum"), loss=("loss", "sum")))
    tot = sub.drop(columns="channel").sum()
    ref = pd.concat([sub, pd.DataFrame([{"channel": -1, **tot}])])
    ref = ref.sort_values(["channel", "sales"], ascending=[True, False])

    got = pd.DataFrame({n: out[n].to_pylist() for n in out.names})
    assert len(got) == len(ref) == 4
    for c in ("channel", "sales", "returns", "profit", "loss"):
        np.testing.assert_array_equal(got[c].values, ref[c].values, err_msg=c)

    # the jitted capped tier agrees with the eager plan row for row
    import jax
    capped, valid, overflow = jax.jit(q5_capped)(tabs, dates)
    assert not bool(overflow)
    m = np.asarray(valid)
    assert m.sum() == 4
    for c in ("channel", "sales", "returns", "profit", "loss"):
        np.testing.assert_array_equal(np.asarray(capped[c].data)[m],
                                      got[c].values, err_msg=c)


def test_nds_q23_pipeline_matches_pandas():
    # structure-level parity, not one scalar: each shared subquery SET and
    # each side's total are asserted in isolation, so a compensating-error
    # pair (e.g. freq too big, best too small) cannot pass
    from benchmarks.bench_nds_q23 import (BEST_FRACTION, FREQ_THRESHOLD,
                                          _datagen, build_tables, q23_detail)
    n_sales = 30_000
    store, sides = build_tables(n_sales, seed=11)
    detail = q23_detail(store, sides)

    s, sd = _datagen(n_sales, seed=11)
    sdf = pd.DataFrame(s)
    freq = sdf.groupby("item_sk").size()
    freq_items = set(freq[freq > FREQ_THRESHOLD].index)
    sdf["rev"] = sdf.qty * sdf.price
    by_cust = sdf.groupby("cust_sk").rev.sum()
    best = set(by_cust[by_cust > BEST_FRACTION * by_cust.max()].index)

    got_freq = set(detail["freq_items"]["item_sk"].to_pylist())
    got_best = set(detail["best_cust"]["cust_sk"].to_pylist())
    assert got_freq == freq_items         # subquery 1 exact set parity
    assert got_best == best               # subquery 2 exact set parity
    assert len(freq_items) > 0 and len(best) > 0

    total = 0
    for side_name, per_side in zip(sd, detail["per_side"]):
        df = pd.DataFrame(sd[side_name])
        df = df[df.item_sk.isin(freq_items) & df.cust_sk.isin(best)]
        side_total = int((df.qty * df.price).sum())
        assert int(per_side) == side_total, side_name   # per-side totals
        total += side_total
    assert int(detail["total"]) == total
    assert total > 0                      # the HAVING clauses selected rows

    # the jitted capped tier: same subquery sets, same per-side totals
    import jax
    from benchmarks.bench_nds_q23 import q23_capped
    capped = jax.jit(q23_capped)(store, sides)
    assert not bool(capped["overflow"])
    fa = np.asarray(capped["freq_alive"])
    ba = np.asarray(capped["best_alive"])
    assert set(np.asarray(capped["freq_keys"])[fa].tolist()) == freq_items
    assert set(np.asarray(capped["best_keys"])[ba].tolist()) == best
    for per_side, want in zip(capped["per_side"], detail["per_side"]):
        assert int(per_side) == int(want)
    assert int(capped["total"]) == total


def test_nds_q72_pipeline_matches_pandas():
    from benchmarks.bench_nds_q72 import _datagen, build_tables, q72
    n_sales = 30_000
    out = q72(*build_tables(n_sales, seed=5))

    cs, inv, items, hd, wh, dates = _datagen(n_sales, seed=5)
    csdf = pd.DataFrame(cs)
    hddf = pd.DataFrame(hd)
    j = csdf.merge(hddf[hddf.hd_buy_potential == 3], left_on="hd_sk",
                   right_on="hd_demo_sk")
    j = j.merge(pd.DataFrame(items), left_on="item_sk", right_on="i_item_sk")
    ddf = pd.DataFrame(dates)
    j = j.merge(ddf[ddf.d_year == 1], left_on="sold_date_sk",
                right_on="d_date_sk")
    j = j[j.ship_days > 5]
    j = j.merge(pd.DataFrame(inv), left_on="i_item_sk",
                right_on="inv_item_sk")
    j = j[(j.inv_week == j.d_week) & (j.inv_qty < j.qty)]
    j = j.merge(pd.DataFrame(wh), left_on="inv_wh_sk",
                right_on="w_warehouse_sk")
    ref = (j.groupby(["i_item_sk", "w_warehouse_sk", "d_week"],
                     as_index=False).size()
            .rename(columns={"size": "cnt"})
            .sort_values(["cnt", "i_item_sk", "w_warehouse_sk", "d_week"],
                         ascending=[False, True, True, True]))

    got = pd.DataFrame({n: out[n].to_pylist() for n in out.names})
    assert len(got) == len(ref)
    assert len(got) > 0
    for c in ("i_item_sk", "w_warehouse_sk", "d_week", "cnt"):
        np.testing.assert_array_equal(got[c].values, ref[c].values, err_msg=c)

    # the jitted capped tier agrees with the eager plan row for row
    import jax
    from benchmarks.bench_nds_q72 import q72_capped
    capped, valid, overflow = jax.jit(q72_capped)(*build_tables(n_sales,
                                                                seed=5))
    assert not bool(overflow)
    m = np.asarray(valid)
    assert m.sum() == len(ref)
    for c in ("i_item_sk", "w_warehouse_sk", "d_week", "cnt"):
        np.testing.assert_array_equal(np.asarray(capped[c].data)[m],
                                      got[c].values, err_msg=c)


def test_nds_q3_capped_autoretry_grows_cap():
    """The single-chip capped tier shares the distributed tier's
    SplitAndRetry contract: a too-small key_cap flags overflow instead of
    corrupting, and parallel.autoretry's generic driver loop grows it
    until the pipeline fits."""
    from spark_rapids_tpu.parallel.autoretry import auto_retry_overflow
    n_sales = 20_000
    sales, dates, items = build_tables(n_sales, seed=7)
    *_, ovf_small = q3_capped(sales, dates, items, key_cap=4)
    assert bool(ovf_small)                # tiny cap must flag, not corrupt
    (out, valid, overflow), caps = auto_retry_overflow(
        lambda key_cap: q3_capped(sales, dates, items, key_cap=key_cap),
        {"key_cap": 4})
    assert not bool(overflow) and caps["key_cap"] > 4
    eager = q3(sales, dates, items)
    m = np.asarray(valid)
    assert m.sum() == eager.num_rows
    np.testing.assert_array_equal(np.asarray(out["revenue"].data)[m],
                                  np.asarray(eager["revenue"].data))
