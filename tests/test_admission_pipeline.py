"""End-to-end: real ops crossing the memory arbiter.

Round-1 verdict: the arbiter was "an island" — no op ever called
`MemoryBudget.acquire`. These tests prove the round-2 wiring: every public
Table op admits its working set through the active `DeviceSession`
(runtime/admission.py), pressure drives the reference's recovery contract
(RetryOOM → rollback → block-until-ready → SplitAndRetryOOM → halve —
RmmSpark.java:402-416), and the spill handler frees *real* HBM buffers
(`jax.Array.delete`), mirroring RmmEventHandlerResourceAdaptor in the
reference's allocator chain (SURVEY.md §3.2).
"""
import gc

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from spark_rapids_tpu import Column, Table
from spark_rapids_tpu.ops import (concat_tables, groupby_aggregate,
                                  halve_table, murmur_hash3_32)
from spark_rapids_tpu.runtime import (DeviceSession, RetryOOM, SpillPool,
                                      operand_nbytes, set_active_session,
                                      with_retry)

from test_resource_adaptor import TaskActor

MiB = 1024 * 1024


@pytest.fixture()
def no_global_session():
    yield
    set_active_session(None)


def _sales_table(n=40_000, n_items=50, seed=7):
    rng = np.random.default_rng(seed)
    items = rng.integers(0, n_items, n).astype(np.int64)
    rev = rng.random(n)
    t = Table([Column.from_numpy(items), Column.from_numpy(rev)],
              names=["item", "rev"])
    pdf = pd.DataFrame({"item": items, "rev": rev})
    return t, pdf


def test_ops_pass_through_without_session():
    # no active session → zero-cost pass-through (the reference only
    # arbitrates once setEventHandler installs the adaptor)
    assert getattr(groupby_aggregate, "__admitted__", False)
    t, pdf = _sales_table(n=1_000)
    agg = groupby_aggregate(t, ["item"], [("rev", "sum")])
    assert agg[0].length == pdf.item.nunique()


def test_pipeline_survives_small_budget(no_global_session):
    """The round-2 mandate test: a groupby whose working set does not fit
    the HBM budget survives via RetryOOM → with_retry → halve_table and
    still produces oracle-exact results, with ≥1 retry recorded."""
    table, pdf = _sales_table()
    input_bytes = operand_nbytes(table)
    # admission reserves 2.0× input bytes for a groupby; budget admits one
    # half-batch but not the full batch
    limit = input_bytes + input_bytes // 2
    session = DeviceSession(limit)
    with session:
        set_active_session(session)
        actor = TaskActor(session, task_id=1).start()
        try:
            def attempt(t):
                return groupby_aggregate(
                    t, ["item"], [("rev", "sum"), ("rev", "count")])

            parts = actor.run(
                lambda: with_retry(session.arbiter, attempt, table,
                                   split=halve_table),
                timeout=120)
            # the full batch cannot be admitted: it must have split
            assert len(parts) >= 2
            retries = session.arbiter.get_and_reset_num_retry_throw(1)
            splits = session.arbiter.get_and_reset_num_split_retry_throw(1)
            assert retries >= 1
            assert splits >= 1

            # merge the partial aggregates (second-phase agg, still admitted)
            def merge():
                cat = concat_tables(
                    [Table(list(p), names=["item", "s", "c"]) for p in parts])
                return groupby_aggregate(cat, ["item"],
                                         [("s", "sum"), ("c", "sum")])

            final = actor.run(merge)
        finally:
            actor.done()

        oracle = pdf.groupby("item").agg(s=("rev", "sum"), c=("rev", "count"))
        got = {int(k): (s, c) for k, s, c in zip(
            final[0].to_pylist(), final[1].to_pylist(), final[2].to_pylist())}
        assert set(got) == set(oracle.index)
        for item, row in oracle.iterrows():
            s, c = got[int(item)]
            assert c == row.c
            np.testing.assert_allclose(s, row.s, rtol=1e-12)


def test_spill_pool_frees_real_device_buffers(no_global_session):
    """Registered cache buffers are actually deleted from the device on
    pressure (handler returns True → the reservation retries immediately,
    with NO task-level RetryOOM — the RmmEventHandlerResourceAdaptor
    fast path)."""
    session = DeviceSession(1 * MiB)
    pool = SpillPool().attach(session.device)
    with session:
        set_active_session(session)
        actor = TaskActor(session, task_id=3).start()
        try:
            cached = jnp.arange(75_000, dtype=jnp.int64)     # ~600 KiB
            buf = actor.run(lambda: pool.register(cached))
            del cached
            assert session.device.used == buf.nbytes

            t = Table([Column.from_numpy(
                np.arange(40_000, dtype=np.int64))])          # 320 KiB input
            # murmur admission wants 1.5×320 KiB; 600 KiB cached + 480 KiB
            # > 1 MiB → the handler must spill, then the op proceeds
            h = actor.run(lambda: murmur_hash3_32(t, seed=42))
            assert h.length == 40_000
            assert buf.spilled
            assert pool.spill_count == 1
            assert pool.spilled_bytes == buf.nbytes
            # fast path: no task-level retry was thrown
            assert session.arbiter.get_and_reset_num_retry_throw(3) == 0

            # restore re-admits through the budget and round-trips the data
            restored = actor.run(buf.get)
            np.testing.assert_array_equal(np.asarray(restored),
                                          np.arange(75_000, dtype=np.int64))
            assert not buf.spilled
            actor.run(lambda: pool.unregister(buf))
            assert session.device.used > 0   # op output still holds its bytes
        finally:
            actor.done()


def test_reservation_follows_output_lifetime(no_global_session):
    """After an op returns, its reservation is shrunk to the outputs' true
    bytes; when the outputs are collected the budget drains to zero (the
    do_deallocate analogue: frees wake the budget)."""
    session = DeviceSession(10 * MiB)
    with session:
        set_active_session(session)
        actor = TaskActor(session, task_id=5).start()
        try:
            col = Column.from_numpy(np.arange(10_000, dtype=np.int64))
            out = actor.run(lambda: murmur_hash3_32(Table([col]), seed=42))
            assert session.device.used == operand_nbytes(out)
            assert 0 < session.device.used < operand_nbytes(col)
            del out
            actor.run(lambda: None)   # flush the actor's last-result ref
            gc.collect()
            assert session.device.used == 0
        finally:
            actor.done()


def test_spillable_table_rollback_and_pinned_use(no_global_session):
    """The spillable-inputs half of the recovery contract
    (RmmSpark.java:402-416): protect() makes an idle task's inputs
    revocable, pressure from another op spills them, get() restores them
    through admission and PINS them so no later pressure can delete arrays
    an op is computing on — pressure against fully-pinned memory falls
    through to the task-level RetryOOM instead."""
    from spark_rapids_tpu.runtime import SpillableTable

    table, pdf = _sales_table(n=30_000)
    input_bytes = operand_nbytes(table)
    # 3.2x: fits inputs (1x) + the groupby working set (2x) when pinned,
    # but not the pressure ops below
    session = DeviceSession(int(3.2 * input_bytes))
    pool = SpillPool().attach(session.device)
    with session:
        set_active_session(session)
        actor = TaskActor(session, task_id=9).start()
        try:
            st = SpillableTable(pool, table)
            actor.run(st.protect)                  # idle: spillable
            assert session.device.used == input_bytes

            # another op's working set (1.5x its 800 KiB input) cannot fit
            # beside the resident inputs: the pool must revoke them
            big = Column.from_numpy(np.arange(100_000, dtype=np.int64))
            h = actor.run(lambda: murmur_hash3_32(Table([big]), seed=1))
            assert pool.spill_count >= 1
            del h, big
            actor.run(lambda: None)
            gc.collect()

            # get() restores through admission and pins; the groupby then
            # runs on guaranteed-live arrays and matches the oracle
            def run_agg():
                t = st.get()               # restores + pins
                return groupby_aggregate(
                    t, ["item"], [("rev", "sum"), ("rev", "count")])

            final = actor.run(run_agg, timeout=60)

            # pinned inputs survive fresh pressure: an op too big for the
            # remaining budget gets RetryOOM (fall-through), and the
            # pinned arrays are still live afterwards
            big2 = Column.from_numpy(
                np.arange(40_000, dtype=np.int64))
            with pytest.raises(RetryOOM):
                actor.run(lambda: murmur_hash3_32(
                    Table([big2, big2, big2, big2]), seed=2), timeout=60)
            # protocol (RmmSpark.java:402): after RetryOOM, acknowledge via
            # block-until-ready; with every byte pinned the arbiter answers
            # with the split escalation, and the doomed op gives up — the
            # thread returns to RUNNING
            from spark_rapids_tpu.runtime import SplitAndRetryOOM
            with pytest.raises(SplitAndRetryOOM):
                actor.run(session.arbiter.block_thread_until_ready,
                          timeout=60)
            again = actor.run(run_agg, timeout=60)
            np.testing.assert_array_equal(np.asarray(final[0].data),
                                          np.asarray(again[0].data))

            # unpin: the inputs are idle again and pressure (the same-sized
            # op as the first spill phase) succeeds by spilling them
            actor.run(st.unpin)
            spills_before = pool.spill_count
            big3 = Column.from_numpy(np.arange(100_000, dtype=np.int64))
            h2 = actor.run(lambda: murmur_hash3_32(Table([big3]), seed=3),
                           timeout=60)
            assert h2.length == 100_000
            assert pool.spill_count > spills_before
            del h2
            actor.run(lambda: None)
            gc.collect()

            # use(): pinned inside the context, spillable after
            def run_use():
                with st.use() as t:
                    return groupby_aggregate(t, ["item"], [("rev", "sum")])
            third = actor.run(run_use, timeout=60)
            assert third[0].length == final[0].length
            assert not any(b.pinned for b in st._unique_buffers())
            actor.run(st.close)
            with pytest.raises(RuntimeError):
                st.get()
        finally:
            actor.done()

        oracle = pdf.groupby("item").agg(s=("rev", "sum"), c=("rev", "count"))
        got = {int(k): (s, c) for k, s, c in zip(
            final[0].to_pylist(), final[1].to_pylist(), final[2].to_pylist())}
        assert set(got) == set(oracle.index)
        for item, row in oracle.iterrows():
            s2, c = got[int(item)]
            assert c == row.c
            np.testing.assert_allclose(s2, row.s, rtol=1e-12)
