"""Auto-retry on distributed overflow (round-2 mandate #6): skewed inputs
that overflow the initial static capacities must converge to correct
results with NO caller intervention — the SplitAndRetry contract in code,
not documentation."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.parallel import (CapacityOverflowError,
                                       auto_retry_overflow,
                                       distributed_groupby,
                                       distributed_inner_join_auto,
                                       distributed_sort_auto, make_mesh)

# Every test here traces a whole shard_map SPMD program — minutes of
# jax tracing that no persistent compilation cache can skip — so the
# module is `slow`: excluded from the timed tier-1 verify, still run
# by ci/premerge.sh and ci/nightly.sh.
pytestmark = pytest.mark.slow


NDEV = 8


def _mesh():
    if len(jax.devices()) < NDEV:
        pytest.skip("needs the 8-device CPU mesh")
    return make_mesh(NDEV)


def _shard(mesh, arr):
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, P("data")))


def test_groupby_overflows_then_heals():
    # one doubling: 40 distinct keys overflow key_cap=32, heal at 64 — the
    # final caps prove the retry happened (each capacity is a separate SPMD
    # trace on a single-core box, so the default tier keeps this to two
    # programs; the deep-escalation variants are nightly)
    mesh = _mesh()
    rng = np.random.default_rng(0)
    n = 8 * 64
    keys = rng.integers(0, 40, n).astype(np.int64)   # 40 keys > key_cap 32
    vals = rng.integers(0, 10, n).astype(np.int64)
    sk, sv = _shard(mesh, keys), _shard(mesh, vals)

    out, caps = auto_retry_overflow(
        lambda key_cap: distributed_groupby(mesh, sk, sv, ["sum"],
                                            key_cap=key_cap),
        {"key_cap": 32})
    gk, (gsum,), gvalid, overflow = out
    assert caps["key_cap"] == 64                     # exactly one retry
    assert not bool(np.asarray(overflow).any())

    got = {}
    v = np.asarray(gvalid)
    k, s = np.asarray(gk), np.asarray(gsum)
    for i in np.nonzero(v)[0]:
        got[int(k[i])] = int(s[i])
    expect = {}
    for kk, vv in zip(keys, vals):
        expect[int(kk)] = expect.get(int(kk), 0) + int(vv)
    assert got == expect


@pytest.mark.nightly
def test_skewed_join_overflows_at_slack_one_then_heals():
    # every left row carries ONE hot key: with slack=1 each shard's bucket
    # for the hot key's home shard spills, and the starting row_cap is far
    # too small for the 64x32 blowup on the hot shard
    mesh = _mesh()
    n = 8 * 8
    lk = np.zeros(n, dtype=np.int64)                 # all rows key 0 (skew)
    lv = np.arange(n, dtype=np.int64)
    rk = np.array([0, 1], dtype=np.int64).repeat(n // 2)
    rv = np.arange(n, dtype=np.int64)
    out = distributed_inner_join_auto(
        mesh, _shard(mesh, lk), _shard(mesh, lv),
        _shard(mesh, rk), _shard(mesh, rv), row_cap=n, slack=1.0,
        max_attempts=8)
    out_lk, out_lv, out_rv, valid, overflow = out
    assert not bool(np.asarray(overflow).any())
    matches = int(np.asarray(valid).sum())
    assert matches == n * (n // 2)                   # n left × n/2 right key-0


def test_skewed_sort_heals():
    mesh = _mesh()
    n = 8 * 32
    keys = np.zeros(n, dtype=np.int64)               # total skew
    keys[: n // 8] = np.arange(n // 8)
    vals = np.arange(n, dtype=np.int64)
    ok, ov, ovalid, overflow = distributed_sort_auto(
        mesh, _shard(mesh, keys), _shard(mesh, vals), slack=1.0)
    assert not bool(np.asarray(overflow).any())
    got_keys = np.asarray(ok)[np.asarray(ovalid)]
    np.testing.assert_array_equal(np.sort(got_keys), np.sort(keys))


def test_retries_exhausted_raises():
    calls = []

    def attempt(cap):
        calls.append(cap)
        return (jnp.zeros(4), jnp.ones(1, bool))     # overflow forever

    with pytest.raises(CapacityOverflowError):
        auto_retry_overflow(attempt, {"cap": 2}, max_attempts=3)
    assert calls == [2, 4, 8]


def test_broadcast_join_auto_grows_row_cap():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from spark_rapids_tpu.parallel import distributed_broadcast_join_auto
    mesh = _mesh()
    ndev = mesh.devices.size
    nl = ndev * 8
    lk = np.zeros(nl, np.int64)           # every left row matches all right
    lv = np.arange(nl, dtype=np.int64)
    rk = np.zeros(ndev, np.int64)
    rv = np.arange(ndev, dtype=np.int64)
    sh = NamedSharding(mesh, P("data"))
    args = [jax.device_put(jnp.asarray(x), sh) for x in (lk, lv, rk, rv)]
    # row_cap=4 per shard overflows (8*ndev matches/shard); auto grows it
    out_lk, out_lv, out_rv, valid, overflow = distributed_broadcast_join_auto(
        mesh, *args, row_cap=4)
    assert not bool(jnp.any(overflow))
    assert int(jnp.sum(valid)) == nl * ndev
