"""Tracing hook tests (reference: NVTX ranges behind the nvtx.enabled flag,
SURVEY.md §5)."""
import os

import numpy as np

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.utils import func_range, range_ctx, trace


def test_disabled_is_passthrough(monkeypatch):
    monkeypatch.delenv("SPARK_RAPIDS_TPU_TRACE", raising=False)

    @func_range
    def f(x):
        return x + 1

    assert f(1) == 2
    with range_ctx("block"):
        pass


def test_enabled_annotates(monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_TRACE", "1")

    @func_range
    def f(x):
        import jax.numpy as jnp
        return jnp.sum(jnp.asarray(x))

    assert int(f(np.arange(10))) == 45
    with range_ctx("block"):
        assert True


def test_device_trace_capture(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp
    d = str(tmp_path / "trace")
    with trace(d):
        jax.block_until_ready(jnp.arange(1000) * 2)
    # a trace directory with at least one xplane artifact appears
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "no trace artifacts written"
