"""Shared micro-benchmark harness + random data generation.

Plays the role of the reference's nvbench + benchmarks/common/generate_input.cu
(SURVEY.md §2.3): every bench file declares configs over named axes, times the
op on-device with warmup (first call compiles under jit; steady-state is what
we report, like nvbench's cold/batched split), and prints one JSON line per
config:

    {"bench": ..., "axes": {...}, "ms": ..., "rows_per_s": ...}

Run any bench file directly, or all of them via `python benchmarks/run_all.py`.
`--scale` shrinks row counts (CI smoke / CPU runs).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Callable, Dict, List, Sequence

import numpy as np

import jax
import jax.numpy as jnp


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0,
                    help="multiply all num_rows axes by this (e.g. 0.01 for smoke)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--sessions", type=int, default=0,
                    help="multi-session serving soak width "
                         "(benchmarks/chaos_soak.py: N concurrent tenant "
                         "sessions through serving/scheduler.py; 0 keeps "
                         "the legacy single-caller soak)")
    ap.add_argument("--workers", type=int, default=0,
                    help="fleet soak width (benchmarks/chaos_soak.py: "
                         "route --sessions tenants across N executor "
                         "workers via serving/fleet.py and kill one "
                         "mid-storm; 0 keeps the single-worker soak)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (CI smoke; the TPU tunnel can "
                         "hang at init — env-var pinning is unreliable under "
                         "the axon sitecustomize, jax.config works)")
    args = ap.parse_args(argv)
    if args.cpu:
        # a too-late pin (backend already initialized) silently no-ops, so
        # check the outcome positively rather than catching anything
        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            print(f"WARNING: --cpu could not pin the platform (backend "
                  f"already initialized as {jax.default_backend()!r}); "
                  f"benches may hit the TPU tunnel", file=sys.stderr)
    return args


def sync(out) -> None:
    """Force execution to complete. `jax.block_until_ready` is NOT a reliable
    barrier on the tunneled axon TPU backend (measured: a 1 GiB copy-add
    "completes" in 20 µs ≈ 98 TB/s); a one-element device→host readback of
    the last dispatched program's output is. Device programs execute in
    order, so reading any output of the final dispatch implies the whole
    chain ran."""
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if isinstance(l, jax.Array) and l.size]
    if not leaves:
        # no readback anchor → the timing loop would measure dispatch only
        print("WARNING: sync(): output has no non-empty jax.Array leaf; "
              "timing will not include device execution", file=sys.stderr)
        return
    np.asarray(jnp.ravel(leaves[-1])[:1])


def steady_state_ms(fn: Callable, args, iters: int, platform: str) -> float:
    """Milliseconds per call of `fn(*args)`, steady-state, on a device of
    `platform`. `fn` must already be compiled/warmed (call it once first).

    Methodology (TPU): the sync barrier (one-element readback, see `sync`)
    costs a full tunnel round-trip (~65 ms measured), so a single timed loop
    would overstate small ops. Time loops of `iters` and `2*iters` and report
    the difference — fixed dispatch+sync overhead cancels, leaving
    per-iteration device time; valid because the TPU executes programs in
    launch order (validated: a 1 GiB u32 copy-add differences to 612 GiB/s rw
    on v5e, ~75% of the 819 GB/s HBM roofline).

    Methodology (CPU): the local client runs programs concurrently on a
    thread pool, so in-order differencing under-counts; instead block each
    iteration's outputs before the next (reliable on the local backend —
    only the tunnel's block_until_ready lies; per-iter blocking also keeps
    one output alive at a time)."""
    if platform == "cpu":
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn(*args))
        return (time.perf_counter() - t0) * 1e3 / iters

    def loop(n: int) -> float:
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = fn(*args)
        sync(r)
        return (time.perf_counter() - t0) * 1e3

    steady_state_ms.last_upper_bound = False
    for _ in range(3):                  # escalate iters while below the
        t1 = loop(iters)                # differencing noise floor
        t2 = loop(2 * iters)
        ms = (t2 - t1) / iters
        if ms > 0:
            return ms
        last_iters = iters
        iters *= 4
    # still non-positive: bounded mean folds the ~65 ms tunnel sync into the
    # per-iter time → an upper bound, flagged so records can say so
    steady_state_ms.last_upper_bound = True
    return t2 / (2 * last_iters)


def emit_record(bench: str, axes: Dict, ms: float, n_rows: int, *,
                impl: str = None, retries: int = None,
                faults_injected: int = None, degraded: bool = None,
                optimizer: str = None, rules_fired: Dict = None,
                io_row_groups_pruned: int = None,
                io_bytes_skipped: int = None,
                io_overlap_ms: float = None,
                mesh_axis: str = None,
                exchange_bytes: int = None,
                exchange_bytes_logical: int = None,
                exchange_bytes_wire: int = None,
                exchange_overlap_ms: float = None,
                kernels=None,
                stats_hits: int = None,
                adaptive: bool = None,
                session: str = None,
                queue_wait_ms: float = None,
                cache_hit: bool = None,
                worker_id: str = None,
                lockdep_edges: int = None,
                lockdep_cycles: int = None,
                **extra) -> Dict:
    """Build + print one bench JSONL record.

    Every record carries `backend` (jax.default_backend() at emit time):
    the bench trajectory has silently compared CPU-fallback runs against
    device runs before (ROADMAP cross-cutting note) — a headline number
    without its backend is not comparable to anything. `n_devices`
    (visible device count at emit time) is stamped the same way: a
    distributed-tier number measured over an N-way mesh is not comparable
    to a single-chip row, and the mesh width must never be inferred from
    the bench name (docs/distributed.md). `adaptive` (whether the
    per-fingerprint stats store was active at emit time) and `stats_hits`
    (the active store's cumulative consult hits) are stamped on EVERY
    row for the same reason (plan/stats.py, docs/adaptive.md): a warm,
    self-tuned number must never silently compare against a cold one.
    Both auto-fill from the active store; pass them explicitly to
    override (e.g. per-phase deltas in benchmarks/adaptive_bench.py).

    Optional distributed fields (the `*_dist` plan variants and the
    nightly distributed-parity/exchange stages record these): `mesh_axis`
    (the mesh axis name the plan was sharded over) and the exchange byte
    counters summed from the per-op metrics — `exchange_bytes` (the WIRE
    bytes the edges shipped, packed form; plan/transport.py), with
    `exchange_bytes_wire` (same number under its explicit name) and
    `exchange_bytes_logical` (unpacked payload) alongside so a JSONL
    consumer can compute the compression ratio without knowing the
    legacy field's meaning; `exchange_overlap_ms` is the async-dispatch
    transfer/compute overlap. lint_metrics enforces that a record
    stamping `exchange_bytes` stamps both named counters too — a wire
    number silently compared against a logical one is the exact
    trajectory bug the backend stamp rule exists for.

    Optional robustness fields (the chaos-soak stage records these, see
    benchmarks/chaos_soak.py / docs/robustness.md): `retries` (fault
    re-runs the plan survived), `faults_injected` (faultinj count drained
    via get_and_reset_injected), `degraded` (result produced by the CPU
    fallback tier after a breaker trip).

    Optional serving fields (the multi-session soak and any bench that
    measures through serving/scheduler.py — docs/serving.md): `session`
    (the tenant session the measured result executed FOR), `queue_wait_ms`
    (submit-to-dispatch wait the fair-share queue imposed), `cache_hit`
    (served from the plan-result cache — a cached number measured no
    execution at all and must never silently compare against a real
    one, the same rule as the backend stamp). lint_metrics enforces that
    a record stamping `queue_wait_ms` or `cache_hit` stamps `session`
    too — a serving number without its tenant is not attributable.
    `worker_id` names the fleet worker that executed (or, for a cache
    hit, COMPUTED) the result (serving/fleet.py); the multi-worker soak
    stamps it on every serving-path row, and lint_metrics enforces the
    stamp the same way it enforces `session`.

    Optional optimizer fields (the plan-tier benches and the nightly
    optimizer-parity stage record these, see docs/optimizer.md):
    `optimizer` ("on"/"off" — which variant this row measured) and
    `rules_fired` (rule -> rewrite count from PlanResult.optimizer), so
    the JSONL history shows the before/after trajectory per rule.

    Optional streaming-IO fields (benchmarks/streaming_scan.py, see
    docs/io.md): `io_row_groups_pruned` (groups skipped via footer
    min/max stats), `io_bytes_skipped` (compressed chunk bytes never
    decoded), `io_overlap_ms` (host decode that ran concurrently with
    execution — the prefetch pipeline's measured win).

    Optional lockdep fields (armed chaos-soak rows, i.e. runs with
    SPARK_RAPIDS_TPU_LOCKDEP=1 — runtime/lockdep.py,
    docs/analysis.md#concurrency-invariants): `lockdep_edges` (observed
    lock-order edge classes accumulated by the witness at emit time)
    and `lockdep_cycles` (observed cycles — any nonzero fails the
    soak). Stamped so the nightly JSONL history shows whether a soak
    row ran under the witness's overhead and how much lock-order
    coverage it exercised.

    Optional kernel-registry field (benchmarks/kernel_bench.py, the
    `*_kernels` plan variants; docs/kernels.md): `kernels` — the per-op
    kernel choices the measured run actually dispatched (a dict like
    {"hash_join": "pallas", ...} from OperatorMetrics.kernel, or the
    string "fallback" when every op ran its universal lowering).
    Trajectory numbers must never silently compare kernel backends —
    the same rule as the `backend` stamp."""
    rec = {"bench": bench, "axes": axes, "ms": round(ms, 3),
           "rows_per_s": round(n_rows / (ms * 1e-3)),
           "backend": jax.default_backend(),
           "n_devices": len(jax.devices())}
    if adaptive is None or stats_hits is None:
        from spark_rapids_tpu.plan import stats as _stats
        store = _stats.active_store()
        if adaptive is None:
            adaptive = store is not None
        if stats_hits is None:
            stats_hits = 0 if store is None else store.hits
    rec["adaptive"] = bool(adaptive)
    rec["stats_hits"] = int(stats_hits)
    if impl is not None:
        rec["impl"] = impl
    if mesh_axis is not None:
        rec["mesh_axis"] = mesh_axis
    if exchange_bytes is not None:
        rec["exchange_bytes"] = exchange_bytes
    if exchange_bytes_logical is not None:
        rec["exchange_bytes_logical"] = exchange_bytes_logical
    if exchange_bytes_wire is not None:
        rec["exchange_bytes_wire"] = exchange_bytes_wire
    if exchange_overlap_ms is not None:
        rec["exchange_overlap_ms"] = round(exchange_overlap_ms, 3)
    if session is not None:
        rec["session"] = session
    if queue_wait_ms is not None:
        rec["queue_wait_ms"] = round(queue_wait_ms, 3)
    if cache_hit is not None:
        rec["cache_hit"] = bool(cache_hit)
    if worker_id is not None:
        rec["worker_id"] = worker_id
    if lockdep_edges is not None:
        rec["lockdep_edges"] = int(lockdep_edges)
    if lockdep_cycles is not None:
        rec["lockdep_cycles"] = int(lockdep_cycles)
    if retries is not None:
        rec["retries"] = retries
    if faults_injected is not None:
        rec["faults_injected"] = faults_injected
    if degraded is not None:
        rec["degraded"] = degraded
    if optimizer is not None:
        rec["optimizer"] = optimizer
    if rules_fired is not None:
        rec["rules_fired"] = rules_fired
    if io_row_groups_pruned is not None:
        rec["io_row_groups_pruned"] = io_row_groups_pruned
    if io_bytes_skipped is not None:
        rec["io_bytes_skipped"] = io_bytes_skipped
    if io_overlap_ms is not None:
        rec["io_overlap_ms"] = round(io_overlap_ms, 3)
    if kernels is not None:
        rec["kernels"] = kernels
    rec.update(extra)
    print(json.dumps(rec), flush=True)
    return rec


def run_config(bench: str, axes: Dict, fn: Callable, args, *, n_rows: int,
               iters: int = 10, jit: bool = True,
               impl: str = None, **record_fields) -> Dict:
    """Time fn(*args) steady-state; returns + prints the result record.

    `jit=True` measures the op as deployed — one compiled XLA program
    (nvbench likewise times the kernel, not per-op dispatch). Ops whose
    output shapes are data-dependent must either take static bounds from the
    bench or pass jit=False. Timing methodology: `steady_state_ms`.

    `impl` names the measured engine/tier (e.g. "capped_jit",
    "plan_capped") and is recorded on the JSONL row, so cross-revision
    history never conflates two engines under one bench name again
    (round-5 ADVICE: the nds_q* configs silently switched engines)."""
    if jit:
        fn = jax.jit(fn)
    out = fn(*args)
    sync(out)                           # compile + warmup
    ms = steady_state_ms(fn, args, iters, jax.default_backend())
    extra = dict(record_fields)         # caller-supplied JSONL fields
    if getattr(steady_state_ms, "last_upper_bound", False):
        extra["ms_upper_bound"] = True  # sync round-trip folded in; see
        # steady_state_ms noise-floor fallback
    return emit_record(bench, axes, ms, n_rows, impl=impl, **extra)


def registry_kernels(*op_names: str) -> Dict:
    """Signature-independent kernel-registry choices for the ops a bench
    dispatches through the public `ops` surface (e.g. "groupby",
    "row_conversion") — the honest `kernels` stamp for non-plan benches
    that still cross the registry. Benches that never dispatch a registry
    op stamp the string "fallback" instead (bench.py's convention:
    stamping choices the run never dispatched would misattribute); plan
    benches stamp the executed result's per-op choices via
    `nds_plans.kernels_of`. Enforced premerge by tools/lint_metrics.py."""
    from spark_rapids_tpu.ops.registry import REGISTRY
    return {op: REGISTRY.select(op, None).name for op in op_names}


# ---- datagen ----------------------------------------------------------------

def random_fixed_table(dts: Sequence, n_rows: int, seed: int = 0):
    """Random Table over fixed-width dtypes (reference create_random_table)."""
    from spark_rapids_tpu import Column, dtypes
    from spark_rapids_tpu.columnar import Table

    rng = np.random.default_rng(seed)
    cols = []
    for i, dt in enumerate(dts):
        np_dt = np.dtype(dt.storage_dtype())
        if np_dt.kind in "iu":
            info = np.iinfo(np_dt)
            arr = rng.integers(info.min, info.max, size=n_rows, dtype=np_dt,
                               endpoint=True)
        elif np_dt.kind == "f":
            arr = rng.standard_normal(n_rows).astype(np_dt) * 1e3
        elif np_dt.kind == "b":
            arr = rng.integers(0, 2, size=n_rows).astype(bool)
        else:
            raise TypeError(f"unsupported bench dtype {dt}")
        cols.append(Column(dtype=dt, length=n_rows, data=jnp.asarray(arr)))
    return Table(cols)


def strings_column_from_list(strs: List[bytes]):
    """Fast path: build a string Column from a list of byte strings via one
    concat + frombuffer, instead of per-row from_pylist."""
    from spark_rapids_tpu.columnar.column import make_string_column

    joined = b"".join(strs)
    chars = np.frombuffer(joined, dtype=np.uint8)
    lens = np.fromiter((len(s) for s in strs), dtype=np.int32, count=len(strs))
    offsets = np.zeros(len(strs) + 1, dtype=np.int32)
    np.cumsum(lens, out=offsets[1:])
    return make_string_column(jnp.asarray(chars), jnp.asarray(offsets))


def random_float_strings(n_rows: int, seed: int = 0):
    """String column holding printed random floats (reference
    cast_string_to_float.cpp:29-34: random FLOAT32 → from_floats)."""
    rng = np.random.default_rng(seed)
    vals = (rng.standard_normal(n_rows) * rng.choice(
        [1e-3, 1.0, 1e4, 1e20], size=n_rows)).astype(np.float32)
    txt = np.char.mod("%g", vals)
    return strings_column_from_list([s.encode() for s in txt.tolist()])


URI_VALID = (b"https://www.example.com/s/query?param0=0&param1=1&param2=2"
             b"&param3=3&param4=4&param5=5&param6=6&param7=7&param8=8")
URI_GARBAGE = [
    b"abcdefghijklmnopqrstuvwxyz 01234" * 8,       # spaces: invalid
    b"",                                           # empty
    "AbcéDEFGHIJKLMNOPQRSTUVWXYZ 01".encode() * 8,  # unicode + spaces: invalid
    b"9876543210,abcdefghijklmnopqrstU" * 8,       # no scheme
]


def uri_mix(n_rows: int, hit_rate: int, seed: int = 0):
    """hit_rate% valid URIs, rest drawn from the garbage pool (reference
    parse_uri.cpp bench_parse_uri hit_rate axis)."""
    rng = np.random.default_rng(seed)
    hits = rng.random(n_rows) < (hit_rate / 100.0)
    pick = rng.integers(0, len(URI_GARBAGE), size=n_rows)
    strs = [URI_VALID if h else URI_GARBAGE[p] for h, p in zip(hits, pick)]
    return strings_column_from_list(strs)
