"""NDS/TPC-DS Q23-shaped end-to-end pipeline (BASELINE.json configs[4]).
Q23 is the *subquery-reuse* query: two expensive subqueries — frequent
items (groupby-HAVING over store_sales) and best customers (per-customer
revenue over a MAX scalar threshold) — are computed once and applied as
IN-filters (semi joins) to BOTH catalog_sales and web_sales, whose filtered
revenues are unioned and totaled.

Shape exercised (all public ops):
    freq_items  = groupby(store_sales, item) count  HAVING count > T
    best_cust   = groupby(store_sales, cust) sum    HAVING sum > 0.95*MAX
    for side in (catalog, web):
        side ⋉ freq_items ⋉ best_cust → sum(qty*price)
    total = sum of both sides                           (one-row output)
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, registry_kernels,  # noqa: E402
                               run_config)

FREQ_THRESHOLD = 4
BEST_FRACTION = 0.95


def _datagen(n_sales: int, seed=0):
    rng = np.random.default_rng(seed)
    n_items, n_cust = 2_000, 5_000
    # zipf-ish skew so HAVING clauses select non-trivial subsets
    items = (rng.zipf(1.3, n_sales) % n_items).astype(np.int64)
    custs = (rng.zipf(1.2, n_sales) % n_cust).astype(np.int64)
    store = {"item_sk": items, "cust_sk": custs,
             "qty": rng.integers(1, 10, n_sales).astype(np.int64),
             "price": rng.integers(1, 1000, n_sales).astype(np.int64)}
    sides = {}
    for name, frac in (("catalog", 2), ("web", 4)):
        m = max(n_sales // frac, 16)
        sides[name] = {
            "item_sk": (rng.zipf(1.3, m) % n_items).astype(np.int64),
            "cust_sk": (rng.zipf(1.2, m) % n_cust).astype(np.int64),
            "qty": rng.integers(1, 10, m).astype(np.int64),
            "price": rng.integers(1, 1000, m).astype(np.int64)}
    return store, sides


def _col(arr):
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=len(arr), data=jnp.asarray(arr))


def _tab(d):
    from spark_rapids_tpu import Table
    return Table([_col(v) for v in d.values()], names=list(d.keys()))


def build_tables(n_sales: int, seed=0):
    store, sides = _datagen(n_sales, seed)
    return _tab(store), {k: _tab(v) for k, v in sides.items()}


def q23(store, sides):
    """The Q23-shaped plan, shared by bench and tests/test_nds_query.py."""
    return q23_detail(store, sides)["total"]


def q23_detail(store, sides):
    """q23 with its intermediate structure exposed (the subquery-reuse
    query's whole point is those two shared subqueries): returns
    {"total", "per_side" [one total per side], "freq_items" Table,
    "best_cust" Table} so the oracle test can assert each subquery set in
    isolation — a compensating-error pair across subqueries cannot pass."""
    import jax.numpy as jnp
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (apply_boolean_mask, groupby_aggregate,
                                      left_semi_join, take_table)

    # subquery 1: frequent items (computed ONCE, used on both sides)
    by_item = groupby_aggregate(store, ["item_sk"], [("qty", "count")])
    freq = Table(list(by_item), names=["item_sk", "cnt"])
    freq = apply_boolean_mask(freq, freq["cnt"].data > FREQ_THRESHOLD)

    # subquery 2: best customers — revenue above 95% of the max revenue
    rev = store["qty"].data * store["price"].data
    store2 = Table(list(store.columns) + [_col_from(rev)],
                   names=list(store.names) + ["rev"])
    by_cust = groupby_aggregate(store2, ["cust_sk"], [("rev", "sum")])
    best = Table(list(by_cust), names=["cust_sk", "rev"])
    max_rev = jnp.max(best["rev"].data)          # the MAX scalar subquery
    best = apply_boolean_mask(
        best, best["rev"].data.astype(jnp.float64) >
              BEST_FRACTION * max_rev.astype(jnp.float64))

    totals = []
    for side in sides.values():
        keep = left_semi_join([side["item_sk"]], [freq["item_sk"]])
        s1 = take_table(side, keep.data)
        keep2 = left_semi_join([s1["cust_sk"]], [best["cust_sk"]])
        s2 = take_table(s1, keep2.data)
        totals.append(jnp.sum(s2["qty"].data * s2["price"].data))
    return {"total": totals[0] + totals[1],   # (1,)-free scalar jax.Array
            "per_side": totals, "freq_items": freq, "best_cust": best}


def _col_from(data):
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=data.shape[0], data=data)


def q23_capped(store, sides, key_cap_items: int = 4096,
               key_cap_cust: int = 8192):
    """q23 as ONE jit-traceable XLA program. Both HAVING subqueries run as
    capped groupbys whose predicate becomes an `alive` mask over the padded
    group table; the IN-filters (semi joins) become semi_join_mask with
    that alive mask as `ralive` — the filtered side never materializes.
    Returns {"total", "per_side", "freq_alive", "best_alive", "freq_keys",
    "best_keys", "overflow"} (same structure q23_detail exposes eagerly)."""
    import jax.numpy as jnp
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (groupby_aggregate_capped,
                                      semi_join_mask)

    by_item, iv, o1 = groupby_aggregate_capped(
        store, ["item_sk"], [("qty", "count")], key_cap=key_cap_items)
    freq = Table(list(by_item), names=["item_sk", "cnt"])
    freq_alive = iv & (freq["cnt"].data > FREQ_THRESHOLD)

    rev = store["qty"].data * store["price"].data
    store2 = Table(list(store.columns) + [_col_from(rev)],
                   names=list(store.names) + ["rev"])
    by_cust, cv, o2 = groupby_aggregate_capped(
        store2, ["cust_sk"], [("rev", "sum")], key_cap=key_cap_cust)
    best = Table(list(by_cust), names=["cust_sk", "rev"])
    revs = best["rev"].data
    max_rev = jnp.max(jnp.where(cv, revs, jnp.iinfo(jnp.int64).min))
    best_alive = cv & (revs.astype(jnp.float64) >
                       BEST_FRACTION * max_rev.astype(jnp.float64))

    totals = []
    for name in ("catalog", "web"):       # dict order is not jit-stable
        side = sides[name]
        hit = (semi_join_mask([side["item_sk"]], [freq["item_sk"]],
                              ralive=freq_alive) &
               semi_join_mask([side["cust_sk"]], [best["cust_sk"]],
                              ralive=best_alive))
        totals.append(jnp.sum(jnp.where(
            hit, side["qty"].data * side["price"].data, 0)))
    return {"total": totals[0] + totals[1], "per_side": totals,
            "freq_alive": freq_alive, "best_alive": best_alive,
            "freq_keys": freq["item_sk"].data, "best_keys": best["cust_sk"].data,
            "overflow": o1 | o2}


def main(argv=None):
    args = parse_args(argv)
    n_sales = max(int(10_000_000 * args.scale), 8192)
    store, sides = build_tables(n_sales)
    n_total = store.num_rows + sum(t.num_rows for t in sides.values())

    def run(s, c, w):
        r = q23_capped(s, {"catalog": c, "web": w})
        return r["total"], r["overflow"]

    # renamed from "nds_q23_pipeline" (round-5 ADVICE: engine-conflating name)
    run_config("nds_q23_pipeline_capped", {"num_rows": n_total}, run,
               (store, sides["catalog"], sides["web"]),
               n_rows=n_total, iters=args.iters,
               jit=True,    # capped static-shape tier: one XLA program
               impl="capped_jit",
               # the hand-written jnp pipeline dispatches the
               # registry groupby inside groupby_aggregate_capped;
               # joins/sorts call the universal lowerings directly
               kernels=registry_kernels("groupby"))

    # plan tier, optimizer off AND on: parity asserted, rows/bytes deltas
    # on the JSONL rows (docs/optimizer.md)
    from benchmarks.nds_plans import (q23_inputs, q23_plan,
                                      run_plan_variants)
    run_plan_variants("nds_q23_pipeline_plan", {"num_rows": n_total},
                      q23_plan(), q23_inputs(store, sides),
                      n_rows=n_total, iters=args.iters,
                      caps=dict(key_cap=8192))


if __name__ == "__main__":
    main()
