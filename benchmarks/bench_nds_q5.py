"""NDS/TPC-DS Q5-shaped end-to-end pipeline (BASELINE.json configs[4]:
"NDS SF100 q5/q23/q72"). Q5 is the *multi-channel rollup*: per channel
(store / catalog / web), sales and returns are UNIONed into one relation,
joined to a date window, aggregated per channel entity, then the three
channels are unioned and rolled up (channel subtotal + grand total).

The shape exercised here (all through public ops, like bench_nds_q3):
    3 x [ concat(sales-as-rows, returns-as-rows) ⋈ date_dim(window)
          → groupby entity_sk: sum(sales), sum(returns), sum(profit) ]
    → add channel tag → concat → groupby (channel) rollup
    → grand-total concat → order by channel, sales desc

Reported rows/s is over total input rows (sales + returns, all channels).
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, registry_kernels,  # noqa: E402
                               run_config)


def _datagen(n_sales: int, seed=0):
    """Three channels; returns are ~10% of sales volume."""
    rng = np.random.default_rng(seed)
    n_dates = 365 * 5
    chans = {}
    for ci, name in enumerate(("store", "catalog", "web")):
        n_s = n_sales // (ci + 1)           # store biggest, web smallest
        n_r = max(n_s // 10, 1)
        chans[name] = {
            "s_sk": rng.integers(0, 1000, n_s).astype(np.int64),
            "s_date": rng.integers(0, n_dates, n_s).astype(np.int64),
            "s_price": rng.integers(1, 10_000, n_s).astype(np.int64),
            "s_profit": rng.integers(-2_000, 5_000, n_s).astype(np.int64),
            "r_sk": rng.integers(0, 1000, n_r).astype(np.int64),
            "r_date": rng.integers(0, n_dates, n_r).astype(np.int64),
            "r_amt": rng.integers(1, 8_000, n_r).astype(np.int64),
            "r_loss": rng.integers(1, 3_000, n_r).astype(np.int64),
        }
    date_sk = np.arange(n_dates, dtype=np.int64)
    return chans, date_sk


DATE_LO, DATE_HI = 700, 714          # the 14-day window of the real q5


def _col(arr):
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=len(arr), data=jnp.asarray(arr))


def build_tables(n_sales: int, seed=0):
    from spark_rapids_tpu import Table
    chans, date_sk = _datagen(n_sales, seed)
    tabs = {}
    for name, c in chans.items():
        tabs[name] = (
            Table([_col(c["s_sk"]), _col(c["s_date"]), _col(c["s_price"]),
                   _col(c["s_profit"])],
                  names=["sk", "date_sk", "sales_price", "profit"]),
            Table([_col(c["r_sk"]), _col(c["r_date"]), _col(c["r_amt"]),
                   _col(c["r_loss"])],
                  names=["sk", "date_sk", "return_amt", "net_loss"]))
    dates = Table([_col(date_sk)], names=["d_date_sk"])
    return tabs, dates


def _const(n, v):
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=n,
                  data=jnp.full((n,), v, jnp.int64))


def _union_channel(sales, returns):
    """UNION ALL of one channel: sales rows carry (price, profit, 0, 0);
    returns carry (0, 0, amt, loss) — the q5 ssr/csr/wsr pattern. Shared by
    the eager and capped plans so their row-for-row parity test compares
    identical inputs."""
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import concat_tables
    ns, nr = sales.num_rows, returns.num_rows
    s_rows = Table([sales["sk"], sales["date_sk"], sales["sales_price"],
                    sales["profit"], _const(ns, 0), _const(ns, 0)],
                   names=["sk", "date_sk", "sales", "profit",
                          "returns", "loss"])
    r_rows = Table([returns["sk"], returns["date_sk"], _const(nr, 0),
                    _const(nr, 0), returns["return_amt"],
                    returns["net_loss"]],
                   names=s_rows.names)
    return concat_tables([s_rows, r_rows])


def q5(tabs, dates):
    """The Q5-shaped plan, shared by bench and tests/test_nds_query.py."""
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (apply_boolean_mask, concat_tables,
                                      groupby_aggregate, inner_join,
                                      sort_table, take_table)

    dwin = apply_boolean_mask(
        dates, (dates["d_date_sk"].data >= DATE_LO) &
               (dates["d_date_sk"].data < DATE_HI))
    const = _const

    per_channel = []
    for ci, (name, (sales, returns)) in enumerate(tabs.items()):
        u = _union_channel(sales, returns)
        lm, _ = inner_join([u["date_sk"]], [dwin["d_date_sk"]])
        uf = take_table(u, lm.data)
        agg = groupby_aggregate(uf, ["sk"],
                                [("sales", "sum"), ("returns", "sum"),
                                 ("profit", "sum"), ("loss", "sum")])
        g = Table(list(agg), names=["sk", "sales", "returns", "profit",
                                    "loss"])
        g = Table([const(g.num_rows, ci)] + list(g.columns),
                  names=["channel"] + list(g.names))
        per_channel.append(g)

    allch = concat_tables(per_channel)
    # rollup level 1: channel subtotals
    by_chan = groupby_aggregate(allch, ["channel"],
                                [("sales", "sum"), ("returns", "sum"),
                                 ("profit", "sum"), ("loss", "sum")])
    sub = Table(list(by_chan), names=["channel", "sales", "returns",
                                      "profit", "loss"])
    # rollup level 2: grand total (groupby on a constant key). Drop both
    # `channel` and `sk` — only the 4 measure columns are aggregated, so the
    # 5 columns here must line up 1:1 with sub.names.
    allc = Table([const(allch.num_rows, -1)] + list(allch.columns)[2:],
                 names=sub.names)
    total = groupby_aggregate(allc, ["channel"],
                              [("sales", "sum"), ("returns", "sum"),
                               ("profit", "sum"), ("loss", "sum")])
    rollup = concat_tables([sub, Table(list(total), names=sub.names)])
    return sort_table(rollup, key_names=["channel", "sales"],
                      ascending=[True, False])


def q5_capped(tabs, dates, key_cap: int = 2048):
    """q5 as ONE jit-traceable XLA program. The date-window join becomes a
    semi-join MASK feeding the groupby's `alive` (d_date_sk is unique, so
    the inner join to the window IS a row filter — the plan a CBO picks);
    per-channel groupbys run capped; the channel/grand-total rollup
    groupbys run over the concatenated PADDED channel outputs (static
    shapes) with the concatenated valid masks as `alive`. Returns
    (Table padded to 16 rollup rows, valid, overflow)."""
    import jax.numpy as jnp
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (concat_tables,
                                      groupby_aggregate_capped,
                                      semi_join_mask, sort_table_capped)

    win = ((dates["d_date_sk"].data >= DATE_LO) &
           (dates["d_date_sk"].data < DATE_HI))
    const = _const

    sums = [("sales", "sum"), ("returns", "sum"), ("profit", "sum"),
            ("loss", "sum")]
    per, pervalid = [], []
    overflow = jnp.asarray(False)
    # fixed channel order: a dict passed through jax.jit is rebuilt with
    # SORTED keys, so enumerate(tabs.items()) would renumber the channels
    # under jit vs eager
    channels = [k for k in ("store", "catalog", "web") if k in tabs]
    channels += [k for k in tabs if k not in channels]
    for ci, name in enumerate(channels):
        sales, returns = tabs[name]
        u = _union_channel(sales, returns)
        alive = semi_join_mask([u["date_sk"]], [dates["d_date_sk"]],
                               ralive=win)
        agg, gvalid, ovf = groupby_aggregate_capped(u, ["sk"], sums,
                                                    key_cap=key_cap,
                                                    alive=alive)
        g = Table(list(agg), names=["sk", "sales", "returns", "profit",
                                    "loss"])
        g = Table([const(key_cap, ci)] + list(g.columns),
                  names=["channel"] + list(g.names))
        per.append(g)
        pervalid.append(gvalid)
        overflow = overflow | ovf

    allch = concat_tables(per)
    av = jnp.concatenate(pervalid)
    by_chan, cvalid, o2 = groupby_aggregate_capped(allch, ["channel"], sums,
                                                   key_cap=8, alive=av)
    sub = Table(list(by_chan), names=["channel", "sales", "returns",
                                      "profit", "loss"])
    allc = Table([const(allch.num_rows, -1)] + list(allch.columns)[2:],
                 names=sub.names)
    total, tvalid, o3 = groupby_aggregate_capped(allc, ["channel"], sums,
                                                 key_cap=8, alive=av)
    rollup = concat_tables([sub, Table(list(total), names=sub.names)])
    rvalid = jnp.concatenate([cvalid, tvalid])
    out, svalid = sort_table_capped(rollup, key_names=["channel", "sales"],
                                    ascending=[True, False], alive=rvalid)
    return out, svalid, overflow | o2 | o3


def main(argv=None):
    args = parse_args(argv)
    n_sales = max(int(10_000_000 * args.scale), 8192)
    tabs, dates = build_tables(n_sales)
    n_total = sum(t.num_rows + r.num_rows for t, r in tabs.values())

    def run(*a):
        t = {k: (a[2 * i], a[2 * i + 1]) for i, k in enumerate(tabs)}
        out, valid, overflow = q5_capped(t, a[-1])
        return [c.data for c in out.columns], valid, overflow

    # renamed from "nds_q5_pipeline" (round-5 ADVICE: engine-conflating name)
    run_config("nds_q5_pipeline_capped", {"num_rows": n_total}, run,
               tuple(x for pair in tabs.values() for x in pair) + (dates,),
               n_rows=n_total, iters=args.iters,
               jit=True,    # capped static-shape tier: one XLA program
               impl="capped_jit",
               # the hand-written jnp pipeline dispatches the
               # registry groupby inside groupby_aggregate_capped;
               # joins/sorts call the universal lowerings directly
               kernels=registry_kernels("groupby"))

    # plan tier, optimizer off AND on: parity asserted, rows/bytes deltas
    # on the JSONL rows (docs/optimizer.md)
    from benchmarks.nds_plans import (dist_mesh, q5_inputs, q5_plan,
                                      run_plan_distributed,
                                      run_plan_kernels,
                                      run_plan_variants)
    run_plan_variants("nds_q5_pipeline_plan", {"num_rows": n_total},
                      q5_plan(), q5_inputs(tabs, dates),
                      n_rows=n_total, iters=args.iters,
                      caps=dict(key_cap=2048))

    # kernel-registry variant (docs/kernels.md): registry-on vs forced-
    # fallback, parity asserted — the named config ci/nightly.sh's
    # kernel_bench speedup gate reads
    run_plan_kernels("nds_q5_pipeline_kernels", {"num_rows": n_total},
                     q5_plan(), q5_inputs(tabs, dates),
                     n_rows=n_total, iters=args.iters,
                     caps=dict(key_cap=2048))

    # distributed tier (docs/distributed.md): the same plan SPMD over a
    # simulated mesh, parity-gated against the single-device eager run
    mesh = dist_mesh()
    if mesh is None:
        print("# nds_q5_pipeline_dist skipped: needs >=4 devices "
              "(set XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    else:
        run_plan_distributed("nds_q5_pipeline_dist", {"num_rows": n_total},
                             q5_plan(), q5_inputs(tabs, dates),
                             n_rows=n_total, iters=args.iters, mesh=mesh)


if __name__ == "__main__":
    main()
