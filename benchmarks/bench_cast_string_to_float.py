"""String→float cast bench (reference benchmarks/cast_string_to_float.cpp).

Axis: num_rows {1M, 16M} (the reference sweeps to 100M on 80 GB GPUs,
:42-44; 16M is the same shape sized to a 16 GB v5e chip — the parse's i32
char planes at 100M rows exceed HBM), input = printed random floats.
"""
import sys

sys.path.insert(0, ".")
from benchmarks.common import parse_args, random_float_strings, run_config  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    from spark_rapids_tpu import dtypes
    from spark_rapids_tpu.ops import string_to_float

    for n_rows in (max(int(1_048_576 * args.scale), 1024),
                   max(int(16_777_216 * args.scale), 2048)):
        col = random_float_strings(n_rows, seed=3)
        # static pad bound so the whole parse jits as one program
        pad = col.padded_chars()[0].shape[1]
        run_config("string_to_float", {"num_rows": n_rows},
                   lambda c: string_to_float(c, dtypes.FLOAT32,
                                             pad_to=pad).data,
                   (col,), n_rows=n_rows, iters=args.iters,
                   kernels="fallback")


if __name__ == "__main__":
    main()
