"""String→float cast bench (reference benchmarks/cast_string_to_float.cpp).

Axis: num_rows {1M, 100M} (reference :42-44), input = printed random floats.
"""
import sys

sys.path.insert(0, ".")
from benchmarks.common import parse_args, random_float_strings, run_config  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    from spark_rapids_tpu import dtypes
    from spark_rapids_tpu.ops import string_to_float

    for n_rows in (max(int(1_048_576 * args.scale), 1024),
                   max(int(104_857_600 * args.scale), 2048)):
        col = random_float_strings(n_rows, seed=3)
        # static pad bound so the whole parse jits as one program
        pad = col.padded_chars()[0].shape[1]
        run_config("string_to_float", {"num_rows": n_rows},
                   lambda c: string_to_float(c, dtypes.FLOAT32,
                                             pad_to=pad).data,
                   (col,), n_rows=n_rows, iters=args.iters)


if __name__ == "__main__":
    main()
