"""The four NDS pipelines as physical plans (spark_rapids_tpu.plan).

One source of truth for the plan-engine form of q3/q5/q23/q72, imported by
BOTH the `_plan` bench configs (bench_nds_q*.py) and the parity tests
(tests/test_plan_nds.py) — the same no-drift contract the hand-wired `q3`
has with test_nds_query.py. Each builder returns a validated Plan whose
EAGER execution matches the hand-wired eager pipeline row for row, and
whose CAPPED execution (one XLA program, plan-level cap escalation) agrees
with the eager result after compaction.

Shapes worth noticing:
- q3/q72: star joins as chained HashJoin nodes; q72's inventory join uses
  the COMPOSITE (item, week) key — the physical plan a CBO picks, and the
  shape that keeps the capped tier fan-out-free (see q72_capped).
- q5: per-channel Union → semi-join date window → rollup via a shared
  Union feeding two aggregates (channel subtotals + the const-key grand
  total).
- q23: the two expensive subqueries are SHARED DAG nodes — both sides
  semi-join the same `freq`/`best` objects, so the executor computes each
  once per run (the subquery-reuse that is the whole point of q23); the
  best-customer HAVING uses a scalar-aggregate expression
  (`> 0.95 * scalar_max(rev)`).
"""
import sys

sys.path.insert(0, ".")

from spark_rapids_tpu.plan import (PlanBuilder, col, lit,  # noqa: E402
                                   scalar_max)


def q3_plan():
    b = PlanBuilder()
    sales = b.scan("sales", schema=["sold_date_sk", "item_sk", "price_cents"])
    dates = (b.scan("dates", schema=["d_date_sk", "d_year", "d_moy"])
             .filter(col("d_moy") == 11))
    items = (b.scan("items", schema=["i_item_sk", "i_brand", "i_manufact"])
             .filter(col("i_manufact") == 42))
    j = (sales.join(dates, left_on="sold_date_sk", right_on="d_date_sk")
              .join(items, left_on="item_sk", right_on="i_item_sk"))
    return (j.aggregate(["d_year", "i_brand"],
                        [("price_cents", "sum", "revenue")])
             .sort(["d_year", "revenue"], ascending=[True, False])
             .build())


def q5_plan():
    from benchmarks.bench_nds_q5 import DATE_HI, DATE_LO
    b = PlanBuilder()
    dates = (b.scan("dates", schema=["d_date_sk"])
             .filter((col("d_date_sk") >= DATE_LO) &
                     (col("d_date_sk") < DATE_HI)))
    sums = [("sales", "sum", "sales"), ("returns", "sum", "returns"),
            ("profit", "sum", "profit"), ("loss", "sum", "loss")]
    per = []
    for ci, name in enumerate(("store", "catalog", "web")):
        s = b.scan(f"{name}_sales",
                   schema=["sk", "date_sk", "sales_price", "profit"])
        r = b.scan(f"{name}_returns",
                   schema=["sk", "date_sk", "return_amt", "net_loss"])
        s_rows = s.project([("sk", col("sk")), ("date_sk", col("date_sk")),
                            ("sales", col("sales_price")),
                            ("profit", col("profit")),
                            ("returns", lit(0)), ("loss", lit(0))])
        r_rows = r.project([("sk", col("sk")), ("date_sk", col("date_sk")),
                            ("sales", lit(0)), ("profit", lit(0)),
                            ("returns", col("return_amt")),
                            ("loss", col("net_loss"))])
        u = (s_rows.union(r_rows)
             .join(dates, left_on="date_sk", right_on="d_date_sk",
                   how="left_semi"))
        g = (u.aggregate(["sk"], sums)
              .project([("channel", lit(ci))] +
                       [(n, col(n)) for n in ("sk", "sales", "returns",
                                              "profit", "loss")]))
        per.append(g)
    allch = PlanBuilder.union(per)
    sub = allch.aggregate(["channel"], sums)
    tot = (allch.project([("channel", lit(-1))] +
                         [(n, col(n)) for n in ("sales", "returns",
                                                "profit", "loss")])
                .aggregate(["channel"], sums))
    return (sub.union(tot)
               .sort(["channel", "sales"], ascending=[True, False])
               .build())


def q23_plan():
    from benchmarks.bench_nds_q23 import BEST_FRACTION, FREQ_THRESHOLD
    b = PlanBuilder()
    schema = ["item_sk", "cust_sk", "qty", "price"]
    store = b.scan("store", schema=schema)
    # subquery 1: frequent items — shared by both sides below
    freq = (store.aggregate(["item_sk"], [("qty", "count", "cnt")])
                 .filter(col("cnt") > FREQ_THRESHOLD))
    # subquery 2: best customers, HAVING sum > fraction * MAX(sum) — the
    # scalar-subquery expression evaluates over live groups only
    best = (store.project([("cust_sk", col("cust_sk")),
                           ("rev", col("qty") * col("price"))])
                 .aggregate(["cust_sk"], [("rev", "sum", "rev")])
                 .filter(col("rev") >
                         lit(BEST_FRACTION) * scalar_max(col("rev"))))
    side_totals = []
    for name in ("catalog", "web"):
        side = b.scan(name, schema=schema)
        tot = (side.join(freq, left_on="item_sk", right_on="item_sk",
                         how="left_semi")
                   .join(best, left_on="cust_sk", right_on="cust_sk",
                         how="left_semi")
                   .project([("rev", col("qty") * col("price"))])
                   .aggregate([], [("rev", "sum", "total")]))
        side_totals.append(tot)
    return (side_totals[0].union(side_totals[1])
            .aggregate([], [("total", "sum", "total")])
            .build())


def q72_plan():
    b = PlanBuilder()
    cs = b.scan("cs", schema=["item_sk", "hd_sk", "sold_date_sk",
                              "ship_days", "qty"])
    inv = b.scan("inv", schema=["inv_item_sk", "inv_week", "inv_wh_sk",
                                "inv_qty"])
    items = b.scan("items", schema=["i_item_sk", "i_brand"])
    hd = (b.scan("hd", schema=["hd_demo_sk", "hd_buy_potential"])
          .filter(col("hd_buy_potential") == 3))
    wh = b.scan("wh", schema=["w_warehouse_sk"])
    dates = (b.scan("dates", schema=["d_date_sk", "d_week", "d_year"])
             .filter(col("d_year") == 1))
    j = (cs.join(hd, "hd_sk", "hd_demo_sk")
           .join(items, "item_sk", "i_item_sk")
           .join(dates, "sold_date_sk", "d_date_sk")
           .filter(col("ship_days") > 5)
           # composite (item, week) key: one inventory row per combo, so
           # the join is fan-out-free (same rows as item-join + week filter)
           .join(inv, ["i_item_sk", "d_week"], ["inv_item_sk", "inv_week"])
           .filter(col("inv_qty") < col("qty"))
           .join(wh, "inv_wh_sk", "w_warehouse_sk"))
    return (j.aggregate(["i_item_sk", "w_warehouse_sk", "d_week"],
                        [("qty", "size", "cnt")])
             .sort(["cnt", "i_item_sk", "w_warehouse_sk", "d_week"],
                   ascending=[False, True, True, True])
             .build())


# ---- optimized/unoptimized bench variants -----------------------------------

def _sink_bytes_in(res) -> int:
    """Bytes entering width-sensitive operators (join/aggregate/sort/
    exchange) of the EXECUTED plan — the per-op metric column pruning is
    expected to reduce (dead columns no longer cross the boundary)."""
    from spark_rapids_tpu.plan import (Exchange, HashAggregate, HashJoin,
                                       Sort, TopK)
    total = 0
    for node in res.plan.nodes:
        if isinstance(node, (HashJoin, HashAggregate, Sort, TopK,
                             Exchange)):
            total += sum(res.metrics[c.label].bytes_out
                         for c in node.children)
    return total


def run_plan_variants(bench: str, axes: dict, plan, inputs, *,
                      n_rows: int, iters: int, caps: dict = None):
    """Time the capped plan tier UNOPTIMIZED then OPTIMIZED, assert result
    parity between the two, and record rows/bytes deltas + optimizer
    fields on the JSONL rows (docs/optimizer.md). Shared by the four
    bench_nds_q*.py plan configs and ci/nightly.sh's optimizer-parity
    stage, so the bench numbers and the parity gate can never drift.

    Runs with the stats store SCOPED OFF: this is the STATIC
    optimizer-off-vs-on A/B — with adaptivity live, the "off" variant's
    execution would record observations the "on" variant consumes, and
    the measured rules_fired/bytes deltas would silently describe a warm
    hybrid instead of the static rules (docs/adaptive.md; the adaptive
    cold/warm trajectory has its own gate, benchmarks/adaptive_bench.py).
    The JSONL rows stamp `adaptive: false` accordingly."""
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.plan import stats as stats_mod
    from benchmarks.common import run_config

    with stats_mod.scoped_store(None):
        return _plan_variants_static(bench, axes, plan, inputs, n_rows,
                                     iters, caps, PlanExecutor, run_config)


def _plan_variants_static(bench, axes, plan, inputs, n_rows, iters, caps,
                          PlanExecutor, run_config):
    results, totals, recs = {}, {}, []
    for optimized in (False, True):
        label = "on" if optimized else "off"
        ex = PlanExecutor(mode="capped", caps=dict(caps or {}),
                          optimize=optimized)
        res = ex.execute(plan, inputs)          # correctness + metrics run
        results[label] = res.compact().to_pydict()
        totals[label] = {
            "plan_rows_out": sum(m.rows_out for m in res.metrics.values()),
            # the per-op frame sum double-counts zero-copy frames
            # (inserted selects, capped-tier Filters), so also record the
            # bytes ENTERING width-sensitive operators — the traffic that
            # actually crosses a join/aggregate/sort materialization
            # boundary, which is what column pruning shrinks
            "plan_bytes_out": sum(m.bytes_out
                                  for m in res.metrics.values()),
            "plan_sink_bytes_in": _sink_bytes_in(res)}
        extra = dict(totals[label])
        rules = None
        if optimized:
            rules = res.optimizer["rules_fired"]
            extra["pruned_columns"] = res.optimizer["pruned_columns"]
            extra["fell_back"] = res.optimizer["fell_back"]
            if res.optimizer.get("fallback"):
                # the verifier's precise diagnostic (which rule, which
                # node, which invariant) — never a bare fell_back flag
                extra["fallback"] = res.optimizer["fallback"]
            # the win the pruned columns bought, in per-op metric terms
            extra["plan_bytes_saved"] = (totals["off"]["plan_bytes_out"]
                                         - totals["on"]["plan_bytes_out"])
            extra["plan_sink_bytes_saved"] = (
                totals["off"]["plan_sink_bytes_in"]
                - totals["on"]["plan_sink_bytes_in"])
            extra["plan_rows_saved"] = (totals["off"]["plan_rows_out"]
                                        - totals["on"]["plan_rows_out"])

        def prun():
            r = ex.execute(plan, inputs)
            return [c.data for c in r.table.columns], r.valid

        recs.append(run_config(
            bench, dict(axes), prun, (), n_rows=n_rows, iters=iters,
            jit=False, impl="plan_capped", optimizer=label,
            rules_fired=rules, kernels=kernels_of(res), **extra))
    assert results["on"] == results["off"], \
        f"{bench}: optimizer changed the result"
    return recs


# ---- kernel-registry (*_kernels) variants -----------------------------------

def kernels_of(res) -> dict:
    """op -> kernel name(s) an executed plan actually dispatched, from the
    per-op OperatorMetrics.kernel stamps (docs/kernels.md). Multiple nodes
    of one op kind may resolve differently (signature declines), so values
    are comma-joined sorted sets."""
    chosen = {}
    for m in res.metrics.values():
        if m.kernel:
            name, _, op = m.kernel.partition(":")
            chosen.setdefault(op, set()).add(name)
    return {op: ",".join(sorted(names))
            for op, names in sorted(chosen.items())}


def run_plan_kernels(bench: str, axes: dict, plan, inputs, *,
                     n_rows: int, iters: int, caps: dict = None):
    """Time the capped plan tier with the kernel registry LIVE and with
    every op forced to its universal fallback
    (SPARK_RAPIDS_TPU_KERNELS=op=fallback,...), assert EXACT result parity
    between the two, and stamp the per-op kernel choices / the "fallback"
    marker on the JSONL rows. These are the named configs behind
    ci/nightly.sh's kernel_bench stage and its capped-tier speedup gate
    (docs/kernels.md). Returns [registry-on record, forced-fallback
    record]."""
    import os
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.ops.registry import REGISTRY
    from benchmarks.common import run_config

    fallback_spec = ",".join(
        f"{op}={next(k.name for k in REGISTRY.kernels(op) if k.fallback)}"
        for op in REGISTRY.ops())
    prev = os.environ.get("SPARK_RAPIDS_TPU_KERNELS")
    results, recs = {}, []
    try:
        for label, spec in (("on", prev), ("fallback", fallback_spec)):
            if spec is None:
                os.environ.pop("SPARK_RAPIDS_TPU_KERNELS", None)
            else:
                os.environ["SPARK_RAPIDS_TPU_KERNELS"] = spec
            ex = PlanExecutor(mode="capped", caps=dict(caps or {}))
            res = ex.execute(plan, inputs)      # correctness + stamps run
            results[label] = res.compact().to_pydict()
            kern = kernels_of(res) if label == "on" else "fallback"

            def prun():
                r = ex.execute(plan, inputs)
                return [c.data for c in r.table.columns], r.valid

            recs.append(run_config(
                bench, dict(axes), prun, (), n_rows=n_rows, iters=iters,
                jit=False, impl="plan_capped", kernels=kern))
    finally:
        if prev is None:
            os.environ.pop("SPARK_RAPIDS_TPU_KERNELS", None)
        else:
            os.environ["SPARK_RAPIDS_TPU_KERNELS"] = prev
    assert results["on"] == results["fallback"], \
        f"{bench}: kernel selection changed the result"
    return recs


# ---- distributed (*_dist) variants ------------------------------------------

def dist_mesh(n_devices: int = 4, axis: str = "data"):
    """A small simulated-CPU mesh for the `*_dist` plan variants, or None
    when the process doesn't have enough devices (benches print a skip
    note instead of failing — the driver must set
    XLA_FLAGS=--xla_force_host_platform_device_count before jax init)."""
    import jax
    from spark_rapids_tpu.parallel import make_mesh
    if len(jax.devices()) < n_devices:
        return None
    return make_mesh(n_devices, axis=axis)


def run_plan_distributed(bench: str, axes: dict, plan, inputs, *,
                         n_rows: int, iters: int, mesh,
                         mesh_axis: str = "data"):
    """Time the full-plan SPMD distributed tier (docs/distributed.md)
    against the single-device eager tier, asserting EXACT result parity,
    and record the distribution facts on the JSONL row: `n_devices`/
    `mesh_axis`/`exchange_bytes` plus the optimizer's exchange selection
    (planned kinds, elisions) and the observed gather count. Shared by
    the bench_nds_q5/q72 `*_dist` configs and ci/nightly.sh's
    distributed-parity stage. Returns (record, PlanResult)."""
    from spark_rapids_tpu.plan import PlanExecutor
    from benchmarks.common import run_config

    ref = PlanExecutor(mode="eager").execute(plan, inputs)
    ex = PlanExecutor(mesh=mesh, mesh_axis=mesh_axis)
    res = ex.execute(plan, inputs)          # correctness + metrics run
    assert not res.degraded, f"{bench}: distributed run degraded to CPU"
    assert res.table.to_pydict() == ref.table.to_pydict(), \
        f"{bench}: distributed result differs from the single-device tier"
    observed = {}
    for m in res.metrics.values():
        if m.exchange_how:
            observed[m.exchange_how] = observed.get(m.exchange_how, 0) + 1
    opt = res.optimizer or {}

    def prun():
        r = ex.execute(plan, inputs)
        return [c.data for c in r.table.columns]

    wire = sum(m.exchange_bytes for m in res.metrics.values())
    rec = run_config(
        bench, dict(axes), prun, (), n_rows=n_rows, iters=iters,
        jit=False, impl="plan_distributed", mesh_axis=mesh_axis,
        kernels=kernels_of(res),
        exchange_bytes=wire,
        exchange_bytes_wire=wire,
        exchange_bytes_logical=sum(m.exchange_bytes_logical
                                   for m in res.metrics.values()),
        exchange_overlap_ms=sum(m.exchange_overlap_ms
                                for m in res.metrics.values()),
        mesh_devices=int(mesh.shape[mesh_axis]),
        exchanges_planned=opt.get("exchanges", {}),
        exchanges_elided=opt.get("exchanges_elided", 0),
        exchanges_observed=observed,
        gathers=observed.get("gather", 0))
    return rec, res


# ---- input bindings ---------------------------------------------------------

def q3_inputs(sales, dates, items):
    return {"sales": sales, "dates": dates, "items": items}


def q5_inputs(tabs, dates):
    out = {"dates": dates}
    for name, (s, r) in tabs.items():
        out[f"{name}_sales"] = s
        out[f"{name}_returns"] = r
    return out


def q23_inputs(store, sides):
    return {"store": store, **sides}


def q72_inputs(cs, inv, items, hd, wh, dates):
    return {"cs": cs, "inv": inv, "items": items, "hd": hd, "wh": wh,
            "dates": dates}
