"""Chunked parquet read → filter → project bench — BASELINE.json configs[3]
("chunked Parquet read → filter → project, single 1GB file"; scaled by
--scale). Measures decode + device transfer + a filter/project pipeline."""
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import parse_args  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    import json

    import jax
    import jax.numpy as jnp
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io import ParquetChunkedReader

    n = max(int(40_000_000 * args.scale), 65_536)   # ~1GB at scale 1
    rng = np.random.default_rng(0)
    t = pa.table({
        "k": pa.array(rng.integers(0, 10_000, n), pa.int64()),
        "v": pa.array(rng.standard_normal(n), pa.float64()),
        "w": pa.array(rng.integers(-10**9, 10**9, n), pa.int64()),
    })
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bench.parquet")
        pq.write_table(t, path, row_group_size=1 << 20, compression="SNAPPY")
        size_mb = os.path.getsize(path) / 1e6

        @jax.jit
        def filter_project(k, v):
            keep = (k % 10) == 0
            return jnp.where(keep, v * 2.0, 0.0).sum()

        t0 = time.perf_counter()
        total = 0.0
        rows = 0
        with ParquetChunkedReader(path, columns=["k", "v"]) as r:
            while r.has_next():
                chunk = r.read_chunk()
                total += float(filter_project(chunk["k"].data,
                                              chunk["v"].data))
                rows += chunk.num_rows
        dt = time.perf_counter() - t0
        import jax
        print(json.dumps({"bench": "parquet_read_filter_project",
                          "axes": {"num_rows": rows,
                                   "file_mb": round(size_mb, 1)},
                          "ms": round(dt * 1e3, 1),
                          "rows_per_s": round(rows / dt),
                          # the cross-cutting stamp rule
                          # (tools/lint_metrics.py): raw reader
                          # bench, no registry op dispatched
                          "backend": jax.default_backend(),
                          "n_devices": len(jax.devices()),
                          "kernels": "fallback"}), flush=True)


if __name__ == "__main__":
    main()
