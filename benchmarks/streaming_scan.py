"""Streaming-scan gate: pruning, parity, and decode/execute overlap.

The nightly stage for the streaming IO subsystem (docs/io.md). One NDS-like
pipeline (Scan -> Filter -> Project -> HashAggregate) runs twice over the
same data — bound to a materialized Table and bound to a parquet file via
`ParquetSource` — and the stage asserts:

1. result parity, eager AND capped tiers (streaming execution is exact);
2. a selective predicate prunes > 0 row groups via footer min/max stats,
   with measurably fewer decoded bytes (`io_bytes_skipped` > 0);
3. with prefetch enabled (SPARK_RAPIDS_TPU_IO_PREFETCH >= 1), host decode
   overlaps plan execution: `io_overlap_ms` > 0.

Emits one JSONL row per variant with the io_* fields + backend
(benchmarks/common.emit_record), so the bench trajectory records what
pruning and pipelining actually bought per revision.
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from benchmarks.common import emit_record, parse_args
from benchmarks.nds_plans import kernels_of

N_ROWS = 400_000
ROW_GROUP = 25_000          # 16 row groups at full scale
# The predicate keeps all but the last two row groups: >= 1 group always
# prunes, and — with at least 8 groups enforced below — the kept chunk
# count always exceeds the prefetch depth + 1, so some decode can only
# start AFTER the consumer frees a queue slot, i.e. during execution:
# measured overlap > 0 is structural, not a timing accident.
KEEP_ROWS = N_ROWS - 2 * ROW_GROUP


def build_file(n_rows: int, path: str, seed: int = 0) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    t = pa.table({
        # monotone column: row groups carry disjoint [min, max] ranges, so
        # a range predicate prunes deterministically
        "seq": pa.array(np.arange(n_rows), pa.int64()),
        "key": pa.array(rng.integers(0, 64, n_rows), pa.int64()),
        "val": pa.array(rng.integers(0, 1_000_000, n_rows), pa.int64()),
        # never projected: its chunks must be skipped, not post-selected
        "pad": pa.array(rng.integers(0, 2**40, n_rows), pa.int64()),
    })
    pq.write_table(t, path, row_group_size=max(1, ROW_GROUP),
                   compression="NONE")


def build_plan(source_kw):
    from spark_rapids_tpu.plan import PlanBuilder, col
    b = PlanBuilder()
    cutoff = KEEP_ROWS
    scan = b.scan("t", **source_kw)
    return (scan.filter((col("seq") < cutoff) & (col("key") >= 8))
                .project([("key", col("key")), ("val", col("val"))])
                .aggregate(["key"], [("val", "sum", "s"),
                                     ("val", "count", "c")])
                .build())


def main() -> int:
    global N_ROWS, KEEP_ROWS
    args = parse_args()
    n_rows = max(ROW_GROUP * 8, int(N_ROWS * args.scale))
    N_ROWS = n_rows
    KEEP_ROWS = n_rows - 2 * ROW_GROUP

    from spark_rapids_tpu import Column, Table
    from spark_rapids_tpu.io import ParquetSource
    from spark_rapids_tpu.plan import PlanExecutor

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "stream.parquet")
        build_file(n_rows, path)
        src = ParquetSource(path)

        import pyarrow.parquet as pq
        pt = pq.read_table(path)
        table = Table([Column.from_numpy(pt[name].to_numpy())
                       for name in pt.column_names],
                      names=list(pt.column_names))

        plan_pq = build_plan({"parquet": src})
        plan_tab = build_plan({"schema": list(pt.column_names)})

        failures = []
        results = {}
        for mode in ("eager", "capped"):
            t0 = time.perf_counter()
            res = PlanExecutor(mode=mode).execute(plan_pq)
            ms = (time.perf_counter() - t0) * 1e3
            ref = PlanExecutor(mode=mode).execute(plan_tab, {"t": table})
            got = (res.compact() if res.valid is not None
                   else res.table).to_pydict()
            want = (ref.compact() if ref.valid is not None
                    else ref.table).to_pydict()
            if got != want:
                failures.append(f"{mode}: parquet-bound result diverges "
                                "from table-bound")
            scan_m = next(m for m in res.metrics.values()
                          if m.kind == "Scan")
            results[mode] = (res, scan_m)
            emit_record("streaming_scan", {"mode": mode, "rows": n_rows},
                        ms, n_rows, impl=f"plan_{mode}",
                        kernels=kernels_of(res),
                        io_row_groups_pruned=scan_m.io_row_groups_pruned,
                        io_bytes_skipped=scan_m.io_bytes_skipped,
                        io_overlap_ms=scan_m.io_overlap_ms,
                        io_row_groups_total=scan_m.io_row_groups_total,
                        io_decode_ms=round(scan_m.io_decode_ms, 3))

        for mode, (res, scan_m) in results.items():
            if scan_m.io_row_groups_pruned <= 0:
                failures.append(
                    f"{mode}: selective predicate pruned 0 of "
                    f"{scan_m.io_row_groups_total} row groups")
            if scan_m.io_bytes_skipped <= 0:
                failures.append(f"{mode}: no decoded bytes were skipped")

        # overlap gate: eager tier only (capped materializes up front),
        # and only when the prefetch pipeline is enabled
        from spark_rapids_tpu import config
        _, eager_scan = results["eager"]
        if config.io_prefetch() >= 1 and eager_scan.io_overlap_ms <= 0:
            failures.append("eager: prefetch enabled but decode/execute "
                            "overlap is 0 ms")

        if failures:
            for f in failures:
                print(f"streaming_scan FAIL: {f}", file=sys.stderr)
            return 1
        print(f"streaming_scan OK: "
              f"{eager_scan.io_row_groups_pruned}/"
              f"{eager_scan.io_row_groups_total} row groups pruned, "
              f"{eager_scan.io_bytes_skipped} B skipped, "
              f"overlap {eager_scan.io_overlap_ms:.3f} ms")
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
