"""NDS/TPC-DS Q3-shaped end-to-end pipeline bench (BASELINE.json north
star: NDS wall-clock parity). The physical plan a Spark executor would run
per batch, driven entirely through the engine's public ops:

    store_sales ⋈ date_dim (d_moy = 11)  ⋈ item (i_manufact_id = M)
      → group by (d_year, i_brand_id) sum(ss_ext_sales_price as int cents)
      → order by d_year, revenue desc

Fact-table scale dominates (star-schema: dims are thousands of rows); the
reported rows/s is over store_sales rows through the whole pipeline.
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, registry_kernels,  # noqa: E402
                               run_config)


def _datagen(n_sales: int, seed=0):
    rng = np.random.default_rng(seed)
    n_dates, n_items = 365 * 10, 20_000         # 10 years, 20k items
    date_sk = np.arange(n_dates, dtype=np.int64)
    d_year = 1998 + date_sk // 365
    d_moy = (date_sk % 365) // 31 + 1
    item_sk = np.arange(n_items, dtype=np.int64)
    i_brand = rng.integers(0, 1000, n_items).astype(np.int64)
    i_manufact = rng.integers(0, 100, n_items).astype(np.int64)
    ss = {
        "sold_date_sk": rng.integers(0, n_dates, n_sales).astype(np.int64),
        "item_sk": rng.integers(0, n_items, n_sales).astype(np.int64),
        "price_cents": rng.integers(1, 10_000, n_sales).astype(np.int64),
    }
    return (date_sk, d_year, d_moy, item_sk, i_brand, i_manufact, ss)


def make_column(arr):
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=len(arr),
                  data=jnp.asarray(arr))


def build_tables(n_sales: int, seed=0):
    from spark_rapids_tpu import Table
    (date_sk, d_year, d_moy, item_sk, i_brand, i_manufact, ss) = \
        _datagen(n_sales, seed)
    col = make_column
    sales = Table([col(ss["sold_date_sk"]), col(ss["item_sk"]),
                   col(ss["price_cents"])],
                  names=["sold_date_sk", "item_sk", "price_cents"])
    dates = Table([col(date_sk), col(d_year), col(d_moy)],
                  names=["d_date_sk", "d_year", "d_moy"])
    items = Table([col(item_sk), col(i_brand), col(i_manufact)],
                  names=["i_item_sk", "i_brand", "i_manufact"])
    return sales, dates, items


def q3(sales, dates, items):
    """The Q3-shaped plan, shared by the bench and tests/test_nds_query.py."""
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (apply_boolean_mask, groupby_aggregate,
                                      inner_join, sort_table, take_table)
    # dim filters first (the plan a CBO picks for a star join); the Table
    # form computes the selection once for all columns
    dates_f = apply_boolean_mask(dates, dates["d_moy"].data == 11)
    items_f = apply_boolean_mask(items, items["i_manufact"].data == 42)
    lm, rm = inner_join([sales["sold_date_sk"]], [dates_f["d_date_sk"]])
    j1 = Table(list(take_table(sales, lm.data).columns) +
               list(take_table(dates_f, rm.data).columns),
               names=list(sales.names) + list(dates_f.names))
    lm2, rm2 = inner_join([j1["item_sk"]], [items_f["i_item_sk"]])
    j2 = Table(list(take_table(j1, lm2.data).columns) +
               list(take_table(items_f, rm2.data).columns),
               names=list(j1.names) + list(items_f.names))
    agg = groupby_aggregate(j2, ["d_year", "i_brand"],
                            [("price_cents", "sum")])
    out = Table(list(agg), names=["d_year", "i_brand", "revenue"])
    return sort_table(out, key_names=["d_year", "revenue"],
                      ascending=[True, False])


def q3_capped(sales, dates, items, key_cap: int = 4096,
              row_cap1: int = 0, row_cap2: int = 0):
    """q3 as ONE jit-traceable XLA program (the engine the bench measures —
    per-op eager dispatch is not the deployed form): dim filters become
    match MASKS (a predicate costs one AND, not a compaction), both star
    joins run capped, the groupby excludes dead join slots via `alive`,
    and the presentation sort sinks dead groups. Returns (Table padded to
    key_cap, valid, overflow) — the SplitAndRetry contract shared with
    parallel/relational.py.

    row_cap1/row_cap2 bound the two join frames; 0 means n_sales (always
    safe: date_sk/item_sk are unique build keys, so each sale matches at
    most one dim row). A selectivity-informed caller passes tighter caps —
    every downstream frame, gather, and the groupby sort shrink with them
    — and relies on the overflow flag + retry to stay safe."""
    import jax.numpy as jnp
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (groupby_aggregate_capped,
                                      inner_join_capped, sort_table_capped,
                                      take)
    n = sales.num_rows
    row_cap1 = row_cap1 or n
    row_cap2 = row_cap2 or n
    dmask = dates["d_moy"].data == 11
    imask = items["i_manufact"].data == 42
    lm1, rm1, v1, o1 = inner_join_capped(
        [sales["sold_date_sk"]], [dates["d_date_sk"]], row_cap=row_cap1,
        ralive=dmask)
    item_sk = take(sales["item_sk"], lm1, _has_negative=False)
    lm2, rm2, v2, o2 = inner_join_capped(
        [item_sk], [items["i_item_sk"]], row_cap=row_cap2, lalive=v1,
        ralive=imask)
    # compose the int32 gather maps once, then fetch each payload column
    # with ONE n-length gather (not one per join level)
    sales2 = jnp.take(lm1, lm2, axis=0)
    dates2 = jnp.take(rm1, lm2, axis=0)
    year = take(dates["d_year"], dates2, _has_negative=False)
    price = take(sales["price_cents"], sales2, _has_negative=False)
    brand = take(items["i_brand"], rm2, _has_negative=False)
    j2 = Table([year, brand, price],
               names=["d_year", "i_brand", "price_cents"])
    agg, gvalid, o3 = groupby_aggregate_capped(
        j2, ["d_year", "i_brand"], [("price_cents", "sum")],
        key_cap=key_cap, alive=v2)
    out = Table(list(agg), names=["d_year", "i_brand", "revenue"])
    out, svalid = sort_table_capped(out, key_names=["d_year", "revenue"],
                                    ascending=[True, False], alive=gvalid)
    return out, svalid, o1 | o2 | o3


def main(argv=None):
    import jax
    args = parse_args(argv)
    n_sales = max(int(10_000_000 * args.scale), 8192)
    sales, dates, items = build_tables(n_sales)

    # selectivity-informed caps (datagen: d_moy==11 keeps ~31/365 of dates,
    # i_manufact==42 ~1/100 of items) with ~1.5-3x headroom; the warmup
    # overflow check below keeps a datagen change from silently timing
    # truncated output (grow like auto_retry_overflow would)
    caps = dict(row_cap1=max(n_sales // 8, 1024),
                row_cap2=max(n_sales // 32, 1024))

    def run(s, d, i):
        return jax_flatten(q3_capped(s, d, i, **caps))

    # one shared jitted callable: the overflow check doubles as warmup
    # (run_config's first call hits the cache), and a raise (not assert:
    # stripped under -O) stops a truncated frame from being timed
    jrun = jax.jit(run)
    if bool(jrun(sales, dates, items)[2]):
        raise RuntimeError("cap overflow: datagen selectivity changed")
    # renamed from "nds_q3_pipeline" (round-5 ADVICE): the old name covered
    # both the eager and the capped engine across revisions
    run_config("nds_q3_pipeline_capped", {"num_sales": n_sales, **caps},
               jrun, (sales, dates, items), n_rows=n_sales,
               iters=args.iters, jit=False,   # already jitted above
               impl="capped_jit",
               # the hand-written jnp pipeline dispatches the
               # registry groupby inside groupby_aggregate_capped;
               # joins/sorts call the universal lowerings directly
               kernels=registry_kernels("groupby"))

    # the same query through the plan engine's capped tier (generic
    # operator DAG; materializes each join frame instead of composing
    # gather maps — the A/B that prices the declarative layer), optimizer
    # off AND on: parity asserted, rows/bytes deltas on the JSONL rows
    from benchmarks.nds_plans import q3_inputs, q3_plan, run_plan_variants
    run_plan_variants("nds_q3_pipeline_plan", {"num_sales": n_sales},
                      q3_plan(), q3_inputs(sales, dates, items),
                      n_rows=n_sales, iters=args.iters,
                      caps=dict(row_cap=caps["row_cap1"], key_cap=4096))


def jax_flatten(res):
    out, valid, overflow = res
    return [c.data for c in out.columns], valid, overflow


if __name__ == "__main__":
    main()
