"""NDS/TPC-DS Q72-shaped end-to-end pipeline (BASELINE.json configs[4]).
Q72 is the *deep multi-join*: catalog_sales chained through inventory,
warehouse, item, household_demographics and three date_dim roles, with a
non-equi residual (inv_quantity_on_hand < cs_quantity) and a date-offset
residual (ship date more than 5 days after sold date), then
groupby + order + limit.

Shape exercised (all public ops):
    catalog_sales ⋈ household_demographics(buy_potential)
                  ⋈ item ⋈ date_dim d1 (year)
                  ⋈ inventory (on item)  ⋈ warehouse
      [residual: inv_qty < cs_qty]  [residual: d_ship > d_sold + 5]
    → groupby (item, warehouse, week) count → order by count desc, keys
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, registry_kernels,  # noqa: E402
                               run_config)


def _datagen(n_sales: int, seed=0):
    rng = np.random.default_rng(seed)
    n_items, n_wh, n_hd, n_dates = 500, 15, 20, 365 * 2
    cs = {"item_sk": rng.integers(0, n_items, n_sales).astype(np.int64),
          "hd_sk": rng.integers(0, n_hd, n_sales).astype(np.int64),
          "sold_date_sk": rng.integers(0, n_dates - 10, n_sales).astype(np.int64),
          "ship_days": rng.integers(0, 14, n_sales).astype(np.int64),
          "qty": rng.integers(1, 20, n_sales).astype(np.int64)}
    # inventory: one row per (item, week) with a quantity on hand
    n_weeks = n_dates // 7
    item_g, week_g = np.meshgrid(np.arange(n_items), np.arange(n_weeks))
    inv = {"inv_item_sk": item_g.ravel().astype(np.int64),
           "inv_week": week_g.ravel().astype(np.int64),
           "inv_wh_sk": rng.integers(0, n_wh, item_g.size).astype(np.int64),
           "inv_qty": rng.integers(0, 25, item_g.size).astype(np.int64)}
    items = {"i_item_sk": np.arange(n_items, dtype=np.int64),
             "i_brand": rng.integers(0, 50, n_items).astype(np.int64)}
    hd = {"hd_demo_sk": np.arange(n_hd, dtype=np.int64),
          "hd_buy_potential": rng.integers(0, 5, n_hd).astype(np.int64)}
    wh = {"w_warehouse_sk": np.arange(n_wh, dtype=np.int64)}
    dates = {"d_date_sk": np.arange(n_dates, dtype=np.int64),
             "d_week": (np.arange(n_dates) // 7).astype(np.int64),
             "d_year": (np.arange(n_dates) // 365).astype(np.int64)}
    return cs, inv, items, hd, wh, dates


def _col(arr):
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=len(arr), data=jnp.asarray(arr))


def _tab(d):
    from spark_rapids_tpu import Table
    return Table([_col(v) for v in d.values()], names=list(d.keys()))


def build_tables(n_sales: int, seed=0):
    return tuple(_tab(d) for d in _datagen(n_sales, seed))


def q72(cs, inv, items, hd, wh, dates):
    """The Q72-shaped plan, shared by bench and tests/test_nds_query.py."""
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (apply_boolean_mask, groupby_aggregate,
                                      inner_join, sort_table, take_table)

    def join(left, lkey, right, rkey):
        lm, rm = inner_join([left[lkey]], [right[rkey]])
        return Table(list(take_table(left, lm.data).columns) +
                     list(take_table(right, rm.data).columns),
                     names=list(left.names) + list(right.names))

    # dim filters first
    hd_f = apply_boolean_mask(hd, hd["hd_buy_potential"].data == 3)
    d1 = apply_boolean_mask(dates, dates["d_year"].data == 1)

    j = join(cs, "hd_sk", hd_f, "hd_demo_sk")              # demographics
    j = join(j, "item_sk", items, "i_item_sk")             # item
    j = join(j, "sold_date_sk", d1, "d_date_sk")           # d1: sold year
    # residual: ship more than 5 days after sold
    j = apply_boolean_mask(j, j["ship_days"].data > 5)
    j = join(j, "i_item_sk", inv, "inv_item_sk")           # inventory (big)
    # residuals: same week on hand, short stock
    j = apply_boolean_mask(j, (j["inv_week"].data == j["d_week"].data) &
                              (j["inv_qty"].data < j["qty"].data))
    j = join(j, "inv_wh_sk", wh, "w_warehouse_sk")         # warehouse

    agg = groupby_aggregate(j, ["i_item_sk", "w_warehouse_sk", "d_week"],
                            [("qty", "size")])
    out = Table(list(agg), names=["i_item_sk", "w_warehouse_sk", "d_week",
                                  "cnt"])
    return sort_table(out,
                      key_names=["cnt", "i_item_sk", "w_warehouse_sk",
                                 "d_week"],
                      ascending=[False, True, True, True])


def q72_capped(cs, inv, items, hd, wh, dates, key_cap: int = 0,
               row_cap: int = 0):
    """q72 as ONE jit-traceable XLA program. Every dim join has a UNIQUE
    build key, so row_cap = n_sales is exact for all of them — including
    inventory, which joins on the COMPOSITE (item, week) key (unique per
    datagen, one row per combo) instead of eager q72's item-only join +
    week filter: same rows, no fan-out, the physical plan a CBO picks.
    Dim filters and the two non-equi residuals are alive-mask ANDs.
    key_cap=0 means row_cap (groups ≤ live rows: never overflows);
    row_cap=0 means n_sales (always safe). A selectivity-informed caller
    passes a tighter row_cap — all five join frames, their gathers, and
    the groupby sort shrink with it — guarded by the overflow flag.
    Returns (Table padded to key_cap, valid, overflow)."""
    import jax.numpy as jnp
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (groupby_aggregate_capped,
                                      inner_join_capped, sort_table_capped,
                                      take)

    n = cs.num_rows
    row_cap = row_cap or n
    key_cap = key_cap or row_cap

    def g(col, m):
        return take(col, m, _has_negative=False)

    def comp(a, b):
        # compose int32 gather maps (dead slots are clamped to 0: in range)
        return jnp.take(a, b, axis=0)

    hd_mask = hd["hd_buy_potential"].data == 3
    d1_mask = dates["d_year"].data == 1

    lm1, _, v1, o1 = inner_join_capped(
        [cs["hd_sk"]], [hd["hd_demo_sk"]], row_cap=row_cap,
        ralive=hd_mask)
    item1 = g(cs["item_sk"], lm1)
    lm2, rm2, v2, o2 = inner_join_capped(
        [item1], [items["i_item_sk"]], row_cap=row_cap, lalive=v1)
    cs2 = comp(lm1, lm2)                 # j2 frame -> cs rows
    sold2 = g(cs["sold_date_sk"], cs2)
    lm3, rm3, v3, o3 = inner_join_capped(
        [sold2], [dates["d_date_sk"]], row_cap=row_cap, lalive=v2,
        ralive=d1_mask)
    cs3 = comp(cs2, lm3)                 # j3 frame -> cs rows
    ship3 = g(cs["ship_days"], cs3)
    v3 = v3 & (ship3.data > 5)                     # date-offset residual
    item3 = g(items["i_item_sk"], comp(rm2, lm3))
    week3 = g(dates["d_week"], rm3)
    lm4, rm4, v4, o4 = inner_join_capped(
        [item3, week3], [inv["inv_item_sk"], inv["inv_week"]],
        row_cap=row_cap, lalive=v3)
    cs4 = comp(cs3, lm4)                 # j4 frame -> cs rows
    qty4 = g(cs["qty"], cs4)
    inv_qty4 = g(inv["inv_qty"], rm4)
    v4 = v4 & (inv_qty4.data < qty4.data)          # short-stock residual
    inv_wh4 = g(inv["inv_wh_sk"], rm4)
    lm5, rm5, v5, o5 = inner_join_capped(
        [inv_wh4], [wh["w_warehouse_sk"]], row_cap=row_cap, lalive=v4)

    j45 = comp(lm4, lm5)                 # j5 frame -> j3 frame
    jt = Table([g(items["i_item_sk"], comp(comp(rm2, lm3), j45)),
                g(wh["w_warehouse_sk"], rm5),
                g(dates["d_week"], comp(rm3, j45)),
                g(cs["qty"], comp(cs3, j45))],
               names=["i_item_sk", "w_warehouse_sk", "d_week", "qty"])
    agg, gvalid, o6 = groupby_aggregate_capped(
        jt, ["i_item_sk", "w_warehouse_sk", "d_week"], [("qty", "size")],
        key_cap=key_cap, alive=v5)
    out = Table(list(agg), names=["i_item_sk", "w_warehouse_sk", "d_week",
                                  "cnt"])
    out, svalid = sort_table_capped(
        out, key_names=["cnt", "i_item_sk", "w_warehouse_sk", "d_week"],
        ascending=[False, True, True, True], alive=gvalid)
    return out, svalid, o1 | o2 | o3 | o4 | o5 | o6


def main(argv=None):
    import jax
    args = parse_args(argv)
    n_sales = max(int(10_000_000 * args.scale), 8192)
    tabs = build_tables(n_sales)
    n = tabs[0].num_rows

    # selectivity-informed caps: seed-0 datagen's hd filter keeps 6/20
    # (0.30), so joins 1-2 hold ~0.30n live rows -> row_cap n/2 is ~1.67x
    # headroom; final groups ~n/45 -> key_cap n/16. The warmup overflow
    # check guards a datagen change.
    caps = dict(row_cap=max(n // 2, 2048), key_cap=max(n // 16, 1024))

    def run(*a):
        out, valid, overflow = q72_capped(*a, **caps)
        return [c.data for c in out.columns], valid, overflow

    # one shared jitted callable: the overflow check doubles as warmup,
    # and a raise (not assert: stripped under -O) stops a truncated frame
    # from being timed
    jrun = jax.jit(run)
    if bool(jrun(*tabs)[2]):
        raise RuntimeError("cap overflow: datagen selectivity changed")
    # renamed from "nds_q72_pipeline" (round-5 ADVICE: engine-conflating name)
    run_config("nds_q72_pipeline_capped", {"num_sales": n, **caps}, jrun,
               tabs, n_rows=n, iters=args.iters,
               jit=False,   # already jitted above
               impl="capped_jit",
               # the hand-written jnp pipeline dispatches the
               # registry groupby inside groupby_aggregate_capped;
               # joins/sorts call the universal lowerings directly
               kernels=registry_kernels("groupby"))

    # plan tier, optimizer off AND on: parity asserted, rows/bytes deltas
    # on the JSONL rows (docs/optimizer.md)
    from benchmarks.nds_plans import (dist_mesh, q72_inputs, q72_plan,
                                      run_plan_distributed,
                                      run_plan_kernels,
                                      run_plan_variants)
    run_plan_variants("nds_q72_pipeline_plan", {"num_sales": n},
                      q72_plan(), q72_inputs(*tabs),
                      n_rows=n, iters=args.iters,
                      caps=dict(row_cap=caps["row_cap"],
                                key_cap=caps["key_cap"]))

    # kernel-registry variant (docs/kernels.md): registry-on vs forced-
    # fallback, parity asserted — the named config ci/nightly.sh's
    # kernel_bench speedup gate reads
    run_plan_kernels("nds_q72_pipeline_kernels", {"num_sales": n},
                     q72_plan(), q72_inputs(*tabs),
                     n_rows=n, iters=args.iters,
                     caps=dict(row_cap=caps["row_cap"],
                               key_cap=caps["key_cap"]))

    # distributed tier (docs/distributed.md): the same plan SPMD over a
    # simulated mesh, parity-gated against the single-device eager run
    mesh = dist_mesh()
    if mesh is None:
        print("# nds_q72_pipeline_dist skipped: needs >=4 devices "
              "(set XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    else:
        run_plan_distributed("nds_q72_pipeline_dist", {"num_sales": n},
                             q72_plan(), q72_inputs(*tabs),
                             n_rows=n, iters=args.iters, mesh=mesh)


if __name__ == "__main__":
    main()
