"""NDS/TPC-DS Q72-shaped end-to-end pipeline (BASELINE.json configs[4]).
Q72 is the *deep multi-join*: catalog_sales chained through inventory,
warehouse, item, household_demographics and three date_dim roles, with a
non-equi residual (inv_quantity_on_hand < cs_quantity) and a date-offset
residual (ship date more than 5 days after sold date), then
groupby + order + limit.

Shape exercised (all public ops):
    catalog_sales ⋈ household_demographics(buy_potential)
                  ⋈ item ⋈ date_dim d1 (year)
                  ⋈ inventory (on item)  ⋈ warehouse
      [residual: inv_qty < cs_qty]  [residual: d_ship > d_sold + 5]
    → groupby (item, warehouse, week) count → order by count desc, keys
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import parse_args, run_config  # noqa: E402


def _datagen(n_sales: int, seed=0):
    rng = np.random.default_rng(seed)
    n_items, n_wh, n_hd, n_dates = 500, 15, 20, 365 * 2
    cs = {"item_sk": rng.integers(0, n_items, n_sales).astype(np.int64),
          "hd_sk": rng.integers(0, n_hd, n_sales).astype(np.int64),
          "sold_date_sk": rng.integers(0, n_dates - 10, n_sales).astype(np.int64),
          "ship_days": rng.integers(0, 14, n_sales).astype(np.int64),
          "qty": rng.integers(1, 20, n_sales).astype(np.int64)}
    # inventory: one row per (item, week) with a quantity on hand
    n_weeks = n_dates // 7
    item_g, week_g = np.meshgrid(np.arange(n_items), np.arange(n_weeks))
    inv = {"inv_item_sk": item_g.ravel().astype(np.int64),
           "inv_week": week_g.ravel().astype(np.int64),
           "inv_wh_sk": rng.integers(0, n_wh, item_g.size).astype(np.int64),
           "inv_qty": rng.integers(0, 25, item_g.size).astype(np.int64)}
    items = {"i_item_sk": np.arange(n_items, dtype=np.int64),
             "i_brand": rng.integers(0, 50, n_items).astype(np.int64)}
    hd = {"hd_demo_sk": np.arange(n_hd, dtype=np.int64),
          "hd_buy_potential": rng.integers(0, 5, n_hd).astype(np.int64)}
    wh = {"w_warehouse_sk": np.arange(n_wh, dtype=np.int64)}
    dates = {"d_date_sk": np.arange(n_dates, dtype=np.int64),
             "d_week": (np.arange(n_dates) // 7).astype(np.int64),
             "d_year": (np.arange(n_dates) // 365).astype(np.int64)}
    return cs, inv, items, hd, wh, dates


def _col(arr):
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    return Column(dtype=dtypes.INT64, length=len(arr), data=jnp.asarray(arr))


def _tab(d):
    from spark_rapids_tpu import Table
    return Table([_col(v) for v in d.values()], names=list(d.keys()))


def build_tables(n_sales: int, seed=0):
    return tuple(_tab(d) for d in _datagen(n_sales, seed))


def q72(cs, inv, items, hd, wh, dates):
    """The Q72-shaped plan, shared by bench and tests/test_nds_query.py."""
    from spark_rapids_tpu import Table
    from spark_rapids_tpu.ops import (apply_boolean_mask, groupby_aggregate,
                                      inner_join, sort_table, take_table)

    def join(left, lkey, right, rkey):
        lm, rm = inner_join([left[lkey]], [right[rkey]])
        return Table(list(take_table(left, lm.data).columns) +
                     list(take_table(right, rm.data).columns),
                     names=list(left.names) + list(right.names))

    # dim filters first
    hd_f = apply_boolean_mask(hd, hd["hd_buy_potential"].data == 3)
    d1 = apply_boolean_mask(dates, dates["d_year"].data == 1)

    j = join(cs, "hd_sk", hd_f, "hd_demo_sk")              # demographics
    j = join(j, "item_sk", items, "i_item_sk")             # item
    j = join(j, "sold_date_sk", d1, "d_date_sk")           # d1: sold year
    # residual: ship more than 5 days after sold
    j = apply_boolean_mask(j, j["ship_days"].data > 5)
    j = join(j, "i_item_sk", inv, "inv_item_sk")           # inventory (big)
    # residuals: same week on hand, short stock
    j = apply_boolean_mask(j, (j["inv_week"].data == j["d_week"].data) &
                              (j["inv_qty"].data < j["qty"].data))
    j = join(j, "inv_wh_sk", wh, "w_warehouse_sk")         # warehouse

    agg = groupby_aggregate(j, ["i_item_sk", "w_warehouse_sk", "d_week"],
                            [("qty", "size")])
    out = Table(list(agg), names=["i_item_sk", "w_warehouse_sk", "d_week",
                                  "cnt"])
    return sort_table(out,
                      key_names=["cnt", "i_item_sk", "w_warehouse_sk",
                                 "d_week"],
                      ascending=[False, True, True, True])


def main(argv=None):
    args = parse_args(argv)
    n_sales = max(int(10_000_000 * args.scale), 8192)
    tabs = build_tables(n_sales)

    run_config("nds_q72_pipeline", {"num_sales": tabs[0].num_rows},
               lambda *a: [c.data for c in q72(*a).columns],
               tabs, n_rows=tabs[0].num_rows, iters=args.iters,
               jit=False)   # join output sizes are data-dependent


if __name__ == "__main__":
    main()
