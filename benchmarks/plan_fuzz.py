"""Nightly deep-fuzz stage (ci/nightly.sh, docs/analysis.md).

Runs the property-based plan fuzzer (spark_rapids_tpu/analysis/fuzz.py)
over a seeded sweep of >=200 random plans — far past the fixed premerge
corpus — asserting every case:

- verifies under the static plan verifier (authored AND optimized form,
  with per-rule re-validation enabled);
- never makes the optimizer fall back;
- (small plans) executes with optimized-vs-unoptimized eager parity,
  including error parity.

Emits one JSONL summary row via benchmarks/common.emit_record with the
seed window, case/executed counts, node-kind coverage and wall time, so
the bench history shows the sweep's trajectory; any failing seed fails
the stage and is replayable with
`python -m spark_rapids_tpu.analysis.fuzz --start <seed> --count 1 -v`.
"""
import argparse
import sys
import time

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args      # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--seed0", type=int, default=1000)
    ap.add_argument("--count", type=int, default=200)
    ap.add_argument("--max-ops", type=int, default=8)
    extra, rest = ap.parse_known_args(argv)
    args = parse_args(rest)                      # --scale/--iters/--cpu
    count = max(int(extra.count * max(args.scale, 0.05)), 50) \
        if args.scale != 1.0 else extra.count

    from spark_rapids_tpu.analysis.fuzz import run_corpus
    t0 = time.perf_counter()
    summary = run_corpus(range(extra.seed0, extra.seed0 + count),
                         execute=True, max_ops=extra.max_ops)
    ms = (time.perf_counter() - t0) * 1e3
    from spark_rapids_tpu.ops.registry import REGISTRY
    emit_record("plan_fuzz", {"seed0": extra.seed0, "count": count,
                              "max_ops": extra.max_ops},
                ms, n_rows=summary["cases"], impl="plan_eager",
                # the sweep's signature-independent registry floor:
                # exact on CPU (accelerator kernels never auto-pick),
                # the conservative floor on device
                kernels=REGISTRY.summary(),
                fuzz_cases=summary["cases"],
                fuzz_executed=summary["executed"],
                fuzz_failures=len(summary["failures"]),
                fuzz_kinds=",".join(summary["kinds_covered"]))
    # report replayable seeds FIRST: a verify/fallback failure also skips
    # execution, and dying on a count assert would swallow the seed the
    # stage's whole contract is to surface
    if summary["failures"]:
        for f in summary["failures"]:
            print(f"FAIL seed {f['seed']}: {f['error']}", file=sys.stderr)
        raise SystemExit(1)
    assert summary["executed"] == summary["cases"], \
        "fuzz: not every case executed"
    # the sweep must exercise the full node vocabulary or it is not the
    # gate it claims to be
    from spark_rapids_tpu.analysis.fuzz import ALL_KINDS
    missing = set(ALL_KINDS) - set(summary["kinds_covered"])
    assert not missing, f"fuzz corpus never generated {sorted(missing)}"
    print(f"plan fuzz OK ({count} plans)", file=sys.stderr)


if __name__ == "__main__":
    main()
