"""Row↔columnar conversion bench (reference benchmarks/row_conversion.cpp).

Axes: num_rows × direction, over the reference's 9-dtype cycle. The general
path runs at 216 columns (reference cycles its 9 dtypes ×212); the
fixed-width-optimized path at 24 columns (it enforces <100 columns / ≤1KB
rows — RowConversion.java:32-34).
"""
import sys

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, random_fixed_table,  # noqa: E402
                               registry_kernels, run_config)

CYCLE = None  # filled in main() once dtypes is importable


def _table(n_cols, n_rows):
    from spark_rapids_tpu import dtypes
    cycle = [dtypes.INT8, dtypes.INT32, dtypes.INT16, dtypes.INT64,
             dtypes.INT32, dtypes.BOOL, dtypes.INT16, dtypes.INT8,
             dtypes.INT64]
    return random_fixed_table([cycle[i % len(cycle)] for i in range(n_cols)],
                              n_rows, seed=7)


def main(argv=None):
    args = parse_args(argv)
    from spark_rapids_tpu.ops import (convert_from_rows, convert_to_rows,
                                      convert_to_rows_fixed_width_optimized)

    for variant, n_cols, to_rows in (
            ("general", 216, convert_to_rows),
            ("fixed_width_optimized", 24, convert_to_rows_fixed_width_optimized)):
        for n_rows in (max(int(262_144 * args.scale), 1024),
                       max(int(1_048_576 * args.scale), 2048)):
            table = _table(n_cols, n_rows)
            schema = [c.dtype for c in table.columns]
            rows = to_rows(table)[0]

            run_config("row_conversion",
                       {"variant": variant, "num_rows": n_rows,
                        "num_cols": n_cols, "direction": "to row"},
                       lambda t, f=to_rows: f(t)[0].children[0].data,
                       (table,), n_rows=n_rows, iters=args.iters,
                       kernels=registry_kernels("row_conversion"))
            run_config("row_conversion",
                       {"variant": variant, "num_rows": n_rows,
                        "num_cols": n_cols, "direction": "from row"},
                       lambda r, s=schema: [c.data for c in
                                            convert_from_rows(r, s).columns],
                       (rows,), n_rows=n_rows, iters=args.iters,
                       kernels=registry_kernels("row_conversion"))


if __name__ == "__main__":
    main()
