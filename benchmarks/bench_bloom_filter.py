"""Bloom filter put bench (reference benchmarks/bloom_filter.cu).

Axis: bloom_filter_bytes {512KiB..8MiB} at fixed row count (reference uses
150M rows / 3 hashes; we scale rows with --scale). Input is xxhash64 of a
random INT64 column, exactly like the reference (:38-39).
"""
import sys

sys.path.insert(0, ".")
from benchmarks.common import parse_args, random_fixed_table, run_config  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    from spark_rapids_tpu import dtypes
    from spark_rapids_tpu.ops import (bloom_filter_create, bloom_filter_probe,
                                      bloom_filter_put, xxhash64)

    num_rows = max(int(150_000_000 * args.scale / 10), 4096)
    num_hashes = 3
    src = random_fixed_table([dtypes.INT64], num_rows, seed=11)
    hashed = xxhash64(src)

    for bf_bytes in (512 << 10, 1 << 20, 2 << 20, 4 << 20, 8 << 20):
        bf = bloom_filter_create(num_hashes, bf_bytes // 8)
        for sort_indices in (False, True):
            run_config("bloom_filter_put",
                       {"bloom_filter_bytes": bf_bytes, "num_rows": num_rows,
                        "sort_indices": sort_indices},
                       lambda c, b=bf, s=sort_indices:
                           bloom_filter_put(b, c, sort_indices=s).bits,
                       (hashed,), n_rows=num_rows, iters=args.iters,
                       kernels="fallback")
        full = bloom_filter_put(bf, hashed)
        run_config("bloom_filter_probe",
                   {"bloom_filter_bytes": bf_bytes, "num_rows": num_rows},
                   lambda c, b=full: bloom_filter_probe(c, b).data,
                   (hashed,), n_rows=num_rows, iters=args.iters,
                   kernels="fallback")


if __name__ == "__main__":
    main()
