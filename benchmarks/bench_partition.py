"""Shuffle bucket-partition bench: A/B of the three histogram/rank paths.

Round-1 chip measurements flagged `searchsorted` (~2 s @ 10M rows) and
emulated scatter-add (~930 ms) — both sit in the sort-based
`build_partition_map`. Contenders:

  sort:   argsort + 2x searchsorted (parallel/shuffle.py, round-1 path)
  scan:   streaming compare-reduce ranks, no sort/searchsorted/scatter-add
          (parallel/partition.py)
  pallas: explicit-kernel histogram, counts resident in VMEM across the
          grid (parallel/partition_pallas.py; histogram only)
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import parse_args, run_config  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.parallel.partition import (build_partition_map_scan,
                                                     partition_histogram)
    from spark_rapids_tpu.parallel.partition_pallas import histogram_pallas
    from spark_rapids_tpu.parallel.shuffle import build_partition_map

    rng = np.random.default_rng(0)
    n_rows = max(int(10_000_000 * args.scale), 4096)
    for P in (8, 64):
        cap = (n_rows // P) * 2
        part = jnp.asarray(rng.integers(0, P, n_rows).astype(np.int32))
        run_config("partition_map_sort", {"num_rows": n_rows, "P": P},
                   lambda p: build_partition_map(p, P, cap), (part,),
                   n_rows=n_rows, iters=args.iters,
                   kernels="fallback")
        run_config("partition_map_scan", {"num_rows": n_rows, "P": P},
                   lambda p: build_partition_map_scan(p, P, cap), (part,),
                   n_rows=n_rows, iters=args.iters,
                   kernels="fallback")
        run_config("histogram_scan", {"num_rows": n_rows, "P": P},
                   lambda p: partition_histogram(p, P), (part,),
                   n_rows=n_rows, iters=args.iters,
                   kernels="fallback")
        interpret = jax.default_backend() != "tpu"
        run_config("histogram_pallas", {"num_rows": n_rows, "P": P},
                   lambda p: histogram_pallas(p, P, interpret=interpret),
                   (part,), n_rows=n_rows, iters=args.iters, jit=False,
                   # not a registry op: this config times the Pallas
                   # histogram directly, so the stamp says so
                   kernels={"histogram": "pallas"})


if __name__ == "__main__":
    main()
