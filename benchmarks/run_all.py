"""Run every micro-bench (reference: the nvbench executables built by
benchmarks/CMakeLists.txt). `python benchmarks/run_all.py --scale 0.01` for a
CPU smoke pass."""
import sys

sys.path.insert(0, ".")

from benchmarks import (bench_bloom_filter, bench_cast_string_to_float,  # noqa: E402
                        bench_groupby, bench_join, bench_parquet_read,
                        bench_nds_q3, bench_parse_uri,
                        bench_partition, bench_row_conversion)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    failures = []
    for mod in (bench_row_conversion, bench_cast_string_to_float,
                bench_bloom_filter, bench_parse_uri, bench_groupby,
                bench_join, bench_parquet_read, bench_partition,
                bench_nds_q3):
        # one family OOMing (e.g. a config sized for a bigger chip) must not
        # take down the rest of the suite — record and continue, like a
        # failed nvbench executable failing its own ctest only
        try:
            mod.main(argv)
        except Exception as e:  # noqa: BLE001
            import json
            import traceback
            traceback.print_exc()
            print(json.dumps({"bench": mod.__name__, "error": repr(e)[:400]}),
                  flush=True)
            failures.append(mod.__name__)
    if failures:
        print(f"FAILED families: {failures}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
