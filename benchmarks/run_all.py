"""Run every micro-bench (reference: the nvbench executables built by
benchmarks/CMakeLists.txt). `python benchmarks/run_all.py --scale 0.01` for a
CPU smoke pass."""
import sys

sys.path.insert(0, ".")

from benchmarks import (bench_bloom_filter, bench_cast_string_to_float,  # noqa: E402
                        bench_groupby, bench_join, bench_parquet_read,
                        bench_nds_q3, bench_parse_uri,
                        bench_partition, bench_row_conversion)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    for mod in (bench_row_conversion, bench_cast_string_to_float,
                bench_bloom_filter, bench_parse_uri, bench_groupby,
                bench_join, bench_parquet_read, bench_partition,
                bench_nds_q3):
        mod.main(argv)


if __name__ == "__main__":
    main()
