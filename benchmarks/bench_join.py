"""Hash-join bench — BASELINE.json configs[2]: "hash inner-join on two
int64-keyed tables, 10M×1M"."""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import parse_args, run_config  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    from spark_rapids_tpu.ops import inner_join

    rng = np.random.default_rng(0)
    nl = max(int(10_000_000 * args.scale), 8192)
    nr = max(int(1_000_000 * args.scale), 1024)
    # ~1 match per left row on average
    lk = Column(dtype=dtypes.INT64, length=nl,
                data=jnp.asarray(rng.integers(0, nr, nl, np.int64)))
    rk = Column(dtype=dtypes.INT64, length=nr,
                data=jnp.asarray(rng.permutation(nr).astype(np.int64)))
    run_config("inner_join", {"left_rows": nl, "right_rows": nr},
               lambda l, r: [c.data for c in inner_join([l], [r])],
               (lk, rk), n_rows=nl, iters=args.iters,
               jit=False)  # match count is data-dependent; kernels jitted in-op


if __name__ == "__main__":
    main()
