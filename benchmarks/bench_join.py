"""Hash-join bench — BASELINE.json configs[2]: "hash inner-join on two
int64-keyed tables, 10M×1M"."""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import parse_args, run_config  # noqa: E402


def main(argv=None):
    args = parse_args(argv)
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, dtypes
    from spark_rapids_tpu.ops import inner_join

    rng = np.random.default_rng(0)
    nl = max(int(10_000_000 * args.scale), 8192)
    nr = max(int(1_000_000 * args.scale), 1024)
    # ~1 match per left row on average
    lk = Column(dtype=dtypes.INT64, length=nl,
                data=jnp.asarray(rng.integers(0, nr, nl, np.int64)))
    rk = Column(dtype=dtypes.INT64, length=nr,
                data=jnp.asarray(rng.permutation(nr).astype(np.int64)))
    run_config("inner_join", {"left_rows": nl, "right_rows": nr},
               lambda l, r: [c.data for c in inner_join([l], [r])],
               (lk, rk), n_rows=nl, iters=args.iters,
               jit=False,  # match count is data-dependent; kernels jitted in-op
               kernels="fallback")  # ops.inner_join IS the universal lowering

    # capped jit tier: the whole join is ONE compiled program, no host sync
    # (~1 match/left row by construction: cap 2x covers it)
    from spark_rapids_tpu.ops import inner_join_capped
    import jax
    # a cap overflow would silently time truncated garbage: check once
    assert not bool(jax.jit(lambda l, r: inner_join_capped(
        [l], [r], row_cap=2 * nl))(lk, rk)[3]), "row_cap overflow"
    run_config("inner_join_capped", {"left_rows": nl, "right_rows": nr,
                                     "row_cap": 2 * nl},
               lambda l, r: inner_join_capped([l], [r], row_cap=2 * nl),
               (lk, rk), n_rows=nl, iters=args.iters, jit=True,
               kernels="fallback")


if __name__ == "__main__":
    main()
