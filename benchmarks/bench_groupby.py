"""Groupby hash-aggregate bench — BASELINE.json configs[1]: "groupby
hash-aggregate (sum/count) on single int32 key, 10M rows"."""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, registry_kernels,  # noqa: E402
                               run_config)


def main(argv=None):
    args = parse_args(argv)
    import jax.numpy as jnp
    from spark_rapids_tpu import Column, Table, dtypes
    from spark_rapids_tpu.ops import groupby_aggregate

    rng = np.random.default_rng(0)
    for n_rows, n_keys in ((max(int(10_000_000 * args.scale), 4096), 100_000),
                           (max(int(10_000_000 * args.scale), 4096), 100)):
        k = Column(dtype=dtypes.INT32, length=n_rows,
                   data=jnp.asarray(rng.integers(0, n_keys, n_rows, np.int32)))
        v = Column(dtype=dtypes.INT64, length=n_rows,
                   data=jnp.asarray(rng.integers(-10**9, 10**9, n_rows, np.int64)))
        t = Table([k, v], names=["k", "v"])
        run_config("groupby_sum_count", {"num_rows": n_rows, "num_keys": n_keys},
                   lambda tb: [c.data for c in groupby_aggregate(
                       tb, ["k"], [("v", "sum"), ("v", "count")]).columns],
                   (t,), n_rows=n_rows, iters=args.iters,
                   jit=False,  # output size is data-dependent (one host
                               # sync); the kernel itself is jitted in-op
                   kernels=registry_kernels("groupby"))

        # capped jit tier: static key_cap output, zero host syncs.
        # min(n_keys, n_rows) keeps smoke-scale caps meaningful (distinct
        # groups are bounded by rows at tiny scales, not the key space)
        from spark_rapids_tpu.ops import groupby_aggregate_capped
        cap = max(2 * min(n_keys, n_rows), 16)

        def capped(tb, cap=cap):
            out, valid, overflow = groupby_aggregate_capped(
                tb, ["k"], [("v", "sum"), ("v", "count")], key_cap=cap)
            # return every output so XLA cannot dead-code the aggregation
            return [c.data for c in out.columns], valid, overflow

        import jax
        # a cap overflow would silently time truncated garbage: check once
        assert not bool(jax.jit(capped)(t)[2]), "key_cap overflow"
        run_config("groupby_sum_count_capped",
                   {"num_rows": n_rows, "num_keys": n_keys, "key_cap": cap},
                   capped, (t,), n_rows=n_rows, iters=args.iters,
                   jit=True, kernels=registry_kernels("groupby"))


if __name__ == "__main__":
    main()
