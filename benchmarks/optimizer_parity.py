"""Nightly optimizer-parity stage (ci/nightly.sh, docs/optimizer.md).

Runs the four NDS plans through the capped plan tier with the rule-based
optimizer OFF and ON (benchmarks/nds_plans.run_plan_variants — the same
helper the bench_nds_q*.py plan configs use), asserting:

- result parity per query (optimized == unoptimized, compacted rows);
- nonzero pruned-column counts on q5 and q72 (the column-pruning rule's
  contract on the shapes that carry dead columns);
- a capped-tier jit-cache hit on a structurally REBUILT plan (the
  fingerprint-keyed program cache: equivalent plans built independently
  share one compiled XLA program).

Emits one JSONL row per (query, optimizer) variant with `optimizer`,
`rules_fired`, `pruned_columns` and plan rows/bytes deltas, plus one
`optimizer_fingerprint_reuse` row recording the cache hit — the BENCH
history shows the before/after trajectory across revisions.
"""
import sys

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args        # noqa: E402
from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,  # noqa: E402
                                  q5_inputs, q5_plan, q23_inputs, q23_plan,
                                  q72_inputs, q72_plan, run_plan_variants)


def main(argv=None):
    args = parse_args(argv)
    n = max(int(100_000 * args.scale), 4000)
    iters = min(args.iters, 3)      # parity gate first, timing second

    from benchmarks.bench_nds_q3 import build_tables as bt3
    from benchmarks.bench_nds_q5 import build_tables as bt5
    from benchmarks.bench_nds_q23 import build_tables as bt23
    from benchmarks.bench_nds_q72 import build_tables as bt72

    cases = {
        "q3": (q3_plan(), q3_inputs(*bt3(n, seed=7)), None),
        "q5": (q5_plan(), q5_inputs(*bt5(n, seed=3)),
               dict(key_cap=2048)),
        "q23": (q23_plan(), q23_inputs(*bt23(n, seed=11)),
                dict(key_cap=8192, row_cap=n)),
        "q72": (q72_plan(), q72_inputs(*bt72(n, seed=5)), None),
    }
    on_rows = {}
    for name, (plan, inputs, caps) in cases.items():
        n_rows = sum(t.num_rows for t in inputs.values())
        recs = run_plan_variants(f"optimizer_parity_{name}",
                                 {"num_rows": n_rows}, plan, inputs,
                                 n_rows=n_rows, iters=iters, caps=caps)
        on = on_rows[name] = next(r for r in recs
                                  if r["optimizer"] == "on")
        assert not on["fell_back"], f"{name}: optimizer fell back"
        assert on["rules_fired"], f"{name}: optimizer fired no rules"
    for name in ("q5", "q72"):
        on = on_rows[name]
        assert on["pruned_columns"] > 0, \
            f"{name}: expected pruned columns, got {on['pruned_columns']}"
        # pruning must show up in the per-op bytes metrics: fewer bytes
        # crossing the join/aggregate/sort materialization boundaries
        assert on["plan_sink_bytes_saved"] > 0, \
            f"{name}: pruning saved no sink bytes ({on})"

    # fingerprint-keyed program reuse: a structurally REBUILT q3 plan must
    # hit the compiled-program cache (no re-trace), recorded in the JSONL.
    # Stats scoped OFF: this asserts the STATIC fingerprint contract — a
    # live store records the first run and could flip an observed-driven
    # decision on the second, changing the optimized fingerprint and
    # re-tracing legitimately (the adaptive trajectory has its own gate,
    # benchmarks/adaptive_bench.py)
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.plan import stats as stats_mod
    _, inputs, _ = cases["q3"]
    with stats_mod.scoped_store(None):
        ex = PlanExecutor(mode="capped")
        ex.execute(q3_plan(), inputs)
        n_programs = len(ex._jit_cache)
        res = ex.execute(q3_plan(), inputs)      # independently rebuilt
        assert res.jit_cache_hits >= 1, "rebuilt plan missed the jit cache"
        assert len(ex._jit_cache) == n_programs, "rebuilt plan re-traced"
        n_rows = sum(t.num_rows for t in inputs.values())
        # emit inside the scope: the row's adaptive stamp must describe
        # the measured (static) run, not the process default at exit
        emit_record("optimizer_fingerprint_reuse", {"num_rows": n_rows},
                    res.wall_ms, n_rows, impl="plan_capped",
                    optimizer="on", jit_cache_hits=res.jit_cache_hits,
                    kernels=kernels_of(res))
    print("optimizer parity OK", file=sys.stderr)


if __name__ == "__main__":
    main()
