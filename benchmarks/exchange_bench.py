"""Nightly exchange-transport stage (ci/nightly.sh, docs/distributed.md
#transport).

Runs NDS q5 and q72 through the full-plan SPMD distributed tier on a
4-device simulated CPU mesh with the packed wire format
(plan/transport.py) and async exchange dispatch forced ON, asserting:

- EXACT result parity per query, four ways: packed+async vs the
  single-device eager tier (inside run_plan_distributed), then
  packed-sync and pack-off runs compared against the packed+async
  result (the transport layer may never change a result);
- compression is REAL: on at least one exchange edge the wire bytes are
  < 0.8x the logical bytes, and no edge's wire ever exceeds its logical;
- KEY narrowing is real too (ISSUE 16): at least one hash edge per
  query compresses below logical AND stamps a `keyN:forB` codec note,
  proving the 8 B key-word planes themselves shrank on the wire;
- the certifier cross-check holds: every planned Exchange edge's wire
  bytes sit at or under its certified per-edge payload bound
  (`footprint.check_observed` — the PR 12 bounds became a runtime
  inequality);
- async dispatch OVERLAPS: summed exchange overlap-ms > 0 on at least
  one query (the transfer ran while the walk executed other operators);
- JSONL rows carry both byte counters plus overlap-ms (run through
  `nds_plans.run_plan_distributed`, so backend/n_devices/kernels stamps
  ride along as always).

Like distributed_parity.py this runs with the stats store scoped OFF so
the static planner's broadcast+shuffle mix is what the edges exercise.
"""
import re
import sys

sys.path.insert(0, ".")

import os  # noqa: E402

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        (flags + " --xla_force_host_platform_device_count=8").strip()

from benchmarks.common import parse_args                     # noqa: E402
from benchmarks.nds_plans import (dist_mesh, q5_inputs,      # noqa: E402
                                  q5_plan, q72_inputs, q72_plan,
                                  run_plan_distributed)

N_DEVICES = 4
RATIO_GATE = 0.8        # wire <= 0.8x logical on >= 1 edge (per ISSUE 14)


def _forced(**env):
    import contextlib

    @contextlib.contextmanager
    def cm():
        prev = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            yield
        finally:
            for k, p in prev.items():
                if p is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = p
    return cm()


def main(argv=None):
    from spark_rapids_tpu.plan import stats as stats_mod
    with stats_mod.scoped_store(None):
        return _main(argv)


def _main(argv=None):
    from spark_rapids_tpu.analysis.footprint import check_observed

    args = parse_args(argv)
    n = max(int(100_000 * args.scale), 10_000)
    iters = min(args.iters, 3)

    from benchmarks.bench_nds_q5 import build_tables as bt5
    from benchmarks.bench_nds_q72 import build_tables as bt72

    mesh = dist_mesh(N_DEVICES)
    assert mesh is not None, \
        f"exchange bench needs >= {N_DEVICES} simulated devices"

    cases = {
        "q5": (q5_plan(), q5_inputs(*bt5(n, seed=3))),
        "q72": (q72_plan(), q72_inputs(*bt72(n, seed=5))),
    }
    best_ratio = 1.0
    total_overlap = 0.0
    key_narrowed = 0
    for name, (plan, inputs) in cases.items():
        n_rows = sum(t.num_rows for t in inputs.values())
        with _forced(SPARK_RAPIDS_TPU_EXCHANGE_PACK="on",
                     SPARK_RAPIDS_TPU_EXCHANGE_ASYNC="on"):
            rec, res = run_plan_distributed(
                f"exchange_bench_{name}", {"num_rows": n_rows}, plan,
                inputs, n_rows=n_rows, iters=iters, mesh=mesh)
        packed = res.table.to_pydict()

        # per-edge honesty + the certifier inequality
        edges = [m for m in res.metrics.values() if m.exchange_how]
        assert edges, f"{name}: no exchange edges observed"
        for m in edges:
            assert m.exchange_bytes <= m.exchange_bytes_logical, \
                (f"{name}: {m.label} wire {m.exchange_bytes} > logical "
                 f"{m.exchange_bytes_logical}")
        ratios = [m.exchange_bytes / m.exchange_bytes_logical
                  for m in edges if m.exchange_bytes_logical]
        best_ratio = min([best_ratio, *ratios])
        # key-word narrowing (ISSUE 16 remainder of ISSUE 14): across
        # the suite at least one standalone HASH edge must both
        # compress below logical and stamp a `keyN:forB` codec note
        # proving the key planes (not just the value planes) shrank on
        # the wire. Fused aggregate exchanges ship int64 partials at
        # wire == logical by design, so the check aggregates over both
        # queries (q5's hash edges all fuse).
        key_narrowed += sum(
            1 for m in edges
            if "hash" in m.exchange_how
            and m.exchange_bytes < m.exchange_bytes_logical
            and re.search(r"\bkey\d+:for\d+", m.exchange_codecs or ""))
        assert res.cert is not None, f"{name}: no resource cert stamped"
        bad = check_observed(res.cert, res)
        assert bad is None, f"{name}: certifier cross-check failed: {bad}"
        assert rec["exchange_bytes_wire"] == rec["exchange_bytes"], name
        assert rec["exchange_bytes_wire"] <= rec["exchange_bytes_logical"]
        total_overlap += rec["exchange_overlap_ms"]

        # transport must never change a result: packed-sync == packed
        # +async == pack-off (run_plan_distributed already asserted
        # packed+async == the single-device eager tier)
        from spark_rapids_tpu.plan import PlanExecutor
        with _forced(SPARK_RAPIDS_TPU_EXCHANGE_PACK="on",
                     SPARK_RAPIDS_TPU_EXCHANGE_ASYNC="off"):
            sync = PlanExecutor(mesh=mesh).execute(plan, inputs)
        assert not sync.degraded, f"{name}: packed-sync run degraded"
        assert sync.table.to_pydict() == packed, \
            f"{name}: async dispatch changed the result"
        with _forced(SPARK_RAPIDS_TPU_EXCHANGE_PACK="off",
                     SPARK_RAPIDS_TPU_EXCHANGE_ASYNC="off"):
            off = PlanExecutor(mesh=mesh).execute(plan, inputs)
        assert not off.degraded, f"{name}: pack-off run degraded"
        assert off.table.to_pydict() == packed, \
            f"{name}: packing changed the result"
        for m in off.metrics.values():
            if m.exchange_how:
                assert m.exchange_bytes == m.exchange_bytes_logical, \
                    f"{name}: pack off but wire != logical on {m.label}"

    assert best_ratio <= RATIO_GATE, \
        (f"no exchange edge compressed below {RATIO_GATE}x logical "
         f"(best ratio {best_ratio:.3f}) — packing is silently "
         "pass-through everywhere")
    assert total_overlap > 0.0, \
        "async dispatch produced zero exchange/compute overlap"
    assert key_narrowed > 0, \
        ("no hash edge narrowed its key-word planes (keyN:forB) — the "
         "ISSUE 16 key-narrowing path never fired")
    print(f"exchange transport OK (best wire/logical {best_ratio:.3f}, "
          f"overlap {total_overlap:.1f} ms, "
          f"{key_narrowed} key-narrowed hash edges)", flush=True)


if __name__ == "__main__":
    main()
