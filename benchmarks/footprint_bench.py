"""Nightly resource-certifier gate (ci/nightly.sh, docs/analysis.md).

Runs NDS q5 and q72 through the eager plan tier COLD then WARM under a
fresh per-fingerprint stats store and asserts the certifier's whole
contract on real query shapes (the fuzzer asserts it on random DAGs):

- SOUNDNESS (gated): for every operator of every run, the observed row
  count lies inside the certified ``[lo, hi]`` interval and the observed
  eager bytes stay at or under the certified byte bound — cold and warm,
  so a stats-driven rewrite can never escape the proof;
- ADMISSION (gated): an executor given a 1-byte certified budget rejects
  the plan with an operator-labelled ResourceAdmissionError BEFORE any
  compilation (the acceptance shape of docs/analysis.md#admission);
- TIGHTNESS (reported, never gated): the certified/observed ratio per
  operator — median and max across the plan — emitted to JSONL per
  (query, phase) row for trend tracking. Bounds are sound by
  construction; this trajectory shows whether they stay USEFUL (a
  certified join bound drifting to 1000x observed is admission noise).
"""
import sys
import time

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args        # noqa: E402
from benchmarks.nds_plans import (kernels_of, q5_inputs,     # noqa: E402
                                  q5_plan, q72_inputs, q72_plan)


def _certify(res, inputs):
    from spark_rapids_tpu.analysis import certify
    from spark_rapids_tpu.analysis.footprint import table_metadata
    dts, nul = table_metadata(inputs)
    return certify(res.plan,
                   bound={n: tuple(t.names) for n, t in inputs.items()},
                   bound_rows={n: t.num_rows for n, t in inputs.items()},
                   input_dtypes=dts, input_nullable=nul)


def _check(name, phase, res, cert):
    """Gated soundness (the single-sourced inequality — the fuzzer's
    property 5 runs the same `check_observed`) + reported tightness."""
    from spark_rapids_tpu.analysis.footprint import check_observed
    bad = check_observed(cert, res)
    assert bad is None, f"{name}/{phase}: certifier unsound — {bad}"
    ratios = sorted(
        b.rows_hi / m.rows_out
        for lbl, m in res.metrics.items()
        for b in (cert.by_label[lbl],)
        if b.rows_hi is not None and m.rows_out > 0)
    if not ratios:
        return {"tightness_rows_median": None, "tightness_rows_max": None}
    return {"tightness_rows_median":
            round(ratios[len(ratios) // 2], 2),
            "tightness_rows_max": round(ratios[-1], 2)}


def _run(name, plan, inputs, n_rows):
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.plan import stats as stats_mod
    from spark_rapids_tpu.analysis.footprint import ResourceAdmissionError

    # admission gate: a 1-byte budget cannot admit anything — the reject
    # must name an operator and land before any compilation
    try:
        PlanExecutor(mode="capped", cert_budget=1).execute(plan,
                                                           dict(inputs))
        raise SystemExit(f"{name}: over-budget plan was admitted")
    except ResourceAdmissionError as e:
        v = e.violations[0]
        assert v.invariant == "footprint.over-budget" and "#" in v.node, \
            f"{name}: admission diagnostic lacks the operator label: {e}"

    results = {}
    # path="": a genuinely cold store, never the persisted operator file
    store = stats_mod.StatsStore(capacity=32, path="")
    for phase in ("cold", "warm"):
        with stats_mod.scoped_store(store):
            ex = PlanExecutor(mode="eager")
            t0 = time.perf_counter()
            res = ex.execute(plan, dict(inputs))
            ms = (time.perf_counter() - t0) * 1e3
            results[phase] = res.compact().to_pydict()
            cert = _certify(res, inputs)
            tight = _check(name, phase, res, cert)
            emit_record(
                f"footprint_{name}", {"phase": phase}, ms, n_rows,
                impl="plan_eager", kernels=kernels_of(res),
                cert_peak_bytes=cert.peak_bytes_hi,
                cert_root_rows_hi=cert.root.rows_hi,
                cert_unbounded_ops=len(cert.unbounded), **tight)
    assert results["cold"] == results["warm"], \
        f"{name}: cold/warm parity broke under the certifier"


def main(argv=None):
    args = parse_args(argv)
    n = max(int(100_000 * args.scale), 5_000)

    from benchmarks.bench_nds_q5 import build_tables as bt5
    from benchmarks.bench_nds_q72 import build_tables as bt72

    q5_in = q5_inputs(*bt5(n, seed=7))
    _run("q5", q5_plan(), q5_in,
         n_rows=sum(t.num_rows for t in q5_in.values()))

    q72_in = q72_inputs(*bt72(n, seed=9))
    _run("q72", q72_plan(), q72_in,
         n_rows=sum(t.num_rows for t in q72_in.values()))
    print("footprint certifier OK", file=sys.stderr)


if __name__ == "__main__":
    main()
