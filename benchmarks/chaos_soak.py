"""Chaos soak: the NDS plan pipelines under a seeded fault-injection config.

The nightly robustness gate (ci/nightly.sh): run q5 and q3 through the plan
engine while `configs/chaos_soak.json` injects a mix of nonfatal faults
(device asserts on joins/aggregates, a substituted return code on projects)
plus ONE fatal fault armed on the first `plan.Sort` interception — and
assert the production recovery story end to end:

1. q5 absorbs the nonfatal faults as backoff-paced retries, then hits the
   fatal at its final Sort: the breaker trips and the plan COMPLETES on the
   degraded CPU tier with result parity against the fault-free run.
2. q3 starts with the breaker open (device quarantined, still poisoned):
   it runs fully degraded without touching the device — parity again.
3. `reset_device()` arms the half-open probation; the heartbeat probe
   closes the breaker and q3 re-runs on the normal path — parity again.

Every run emits a bench JSONL row with the robustness fields (`retries`,
`faults_injected`, `degraded` — benchmarks/common.py emit_record), so the
nightly log shows how much chaos the engine actually absorbed. The soak
FAILS (non-zero exit) on any parity miss, on zero injected faults, zero
retries, or zero degraded completions — a silently-ineffective fault config
must not pass as green.
"""
import os
import sys
import time

# keep retry pacing out of the nightly wall-clock (config reads at use time)
os.environ.setdefault("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_BASE_MS", "1")
os.environ.setdefault("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_MAX_MS", "8")

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args  # noqa: E402

CONFIG = os.path.join(os.path.dirname(__file__), os.pardir, "configs",
                      "chaos_soak.json")


def _run(ex, plan, inputs):
    t0 = time.perf_counter()
    res = ex.execute(plan, inputs)
    return res, (time.perf_counter() - t0) * 1e3


def main(argv=None):
    args = parse_args(argv)
    from spark_rapids_tpu import faultinj
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.runtime.health import HALF_OPEN
    from benchmarks.bench_nds_q3 import build_tables as q3_tables
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,
                                      q5_inputs, q5_plan)

    n = max(2000, int(30_000 * args.scale))
    sales, dates3, items = q3_tables(n, seed=7)
    tabs, dates5 = q5_tables(n, seed=3)
    plans = {"q5": (q5_plan(), q5_inputs(tabs, dates5)),
             "q3": (q3_plan(), q3_inputs(sales, dates3, items))}

    # fault-free references (and compile warm-up) before the injector loads
    ex = PlanExecutor(mode="eager")
    refs = {q: ex.execute(p, i).table.to_pydict()
            for q, (p, i) in plans.items()}

    inj = faultinj.install(CONFIG)
    totals = {"retries": 0, "faults": 0, "degraded": 0}
    try:
        def soak(q, expect_degraded=None):
            plan, inputs = plans[q]
            res, ms = _run(ex, plan, inputs)
            faults = inj.get_and_reset_injected()
            if res.table.to_pydict() != refs[q]:
                raise SystemExit(f"chaos soak: {q} parity MISS "
                                 f"(degraded={res.degraded})")
            if expect_degraded is not None and res.degraded != expect_degraded:
                raise SystemExit(f"chaos soak: {q} degraded={res.degraded}, "
                                 f"expected {expect_degraded} "
                                 f"(breaker {res.breaker})")
            totals["retries"] += res.retries
            totals["faults"] += faults
            totals["degraded"] += int(res.degraded)
            emit_record("chaos_soak", {"query": q, "rows": n}, ms, n,
                        impl="plan_eager", retries=res.retries,
                        kernels=kernels_of(res),
                        faults_injected=faults, degraded=res.degraded,
                        breaker=res.breaker["state"])
            return res

        # 1. nonfatal storm + the one fatal (first plan.Sort): degrades
        soak("q5", expect_degraded=True)
        # 2. breaker open, device poisoned: full plans stay on the CPU tier
        soak("q3", expect_degraded=True)
        # 3. operator intervention: reset + half-open probe -> normal tier
        ex.health.reset_device()
        assert ex.health.breaker.state == HALF_OPEN
        res = soak("q3", expect_degraded=False)
        if res.breaker["state"] != "closed":
            raise SystemExit(f"chaos soak: breaker failed to close after "
                             f"reset_device ({res.breaker})")
    finally:
        faultinj.uninstall()

    health = ex.health.get_and_reset_metrics()
    if totals["faults"] == 0 or totals["retries"] == 0 \
            or totals["degraded"] == 0:
        raise SystemExit(f"chaos soak ineffective: {totals} (health "
                         f"counters {health}) — fault config injected "
                         "nothing worth recovering from")
    print(f"chaos soak OK: {totals['faults']} faults injected, "
          f"{totals['retries']} retries, {totals['degraded']} degraded "
          f"completions, breaker closed")


if __name__ == "__main__":
    main()
