"""Chaos soak: the NDS plan pipelines under a seeded fault-injection config.

The nightly robustness gate (ci/nightly.sh): run q5 and q3 through the plan
engine while `configs/chaos_soak.json` injects a mix of nonfatal faults
(device asserts on joins/aggregates, a substituted return code on projects)
plus ONE fatal fault armed on the first `plan.Sort` interception — and
assert the production recovery story end to end:

1. q5 absorbs the nonfatal faults as backoff-paced retries, then hits the
   fatal at its final Sort: the breaker trips and the plan COMPLETES on the
   degraded CPU tier with result parity against the fault-free run.
2. q3 starts with the breaker open (device quarantined, still poisoned):
   it runs fully degraded without touching the device — parity again.
3. `reset_device()` arms the half-open probation; the heartbeat probe
   closes the breaker and q3 re-runs on the normal path — parity again.

Every run emits a bench JSONL row with the robustness fields (`retries`,
`faults_injected`, `degraded` — benchmarks/common.py emit_record), so the
nightly log shows how much chaos the engine actually absorbed. The soak
FAILS (non-zero exit) on any parity miss, on zero injected faults, zero
retries, or zero degraded completions — a silently-ineffective fault config
must not pass as green.

Multi-session serving soak (`--sessions N`, docs/serving.md): the same
chaos config hammers N concurrent tenant sessions submitting a mixed
q3/q5 workload through `serving.ServingScheduler` — the realistic
mixed-workload load test the ROADMAP promised this harness would become.
Asserts: bit-exact result parity against the fault-free solo run for
EVERY session's EVERY completion (device, degraded, or cached), zero
failed/starved sessions with a bounded p99 queue wait, >= 1
parity-checked device-tier cache hit scheduler-wide after recovery
(degraded results never cache, so most chaos-phase sessions legitimately
finish hit-less), and the same injected-chaos effectiveness floor as the
legacy mode. Emits one JSONL row per session with the serving stamps
(`session`, `queue_wait_ms`, `cache_hit` — lint_metrics-enforced).
"""
import os
import sys
import time

# keep retry pacing out of the nightly wall-clock (config reads at use time)
os.environ.setdefault("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_BASE_MS", "1")
os.environ.setdefault("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_MAX_MS", "8")

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args  # noqa: E402

CONFIG = os.path.join(os.path.dirname(__file__), os.pardir, "configs",
                      "chaos_soak.json")


def _run(ex, plan, inputs):
    t0 = time.perf_counter()
    res = ex.execute(plan, inputs)
    return res, (time.perf_counter() - t0) * 1e3


def soak_serving(args):
    """`--sessions N` mode: N tenants through the serving scheduler under
    the seeded chaos config (module docstring, docs/serving.md)."""
    from spark_rapids_tpu import faultinj
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.runtime.health import DeviceHealthMonitor
    from spark_rapids_tpu.serving import ServingScheduler
    from benchmarks.bench_nds_q3 import build_tables as q3_tables
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,
                                      q5_inputs, q5_plan)

    n_sessions = args.sessions
    n = max(2000, int(30_000 * args.scale))
    sales, dates3, items = q3_tables(n, seed=7)
    tabs, dates5 = q5_tables(n, seed=3)
    plans = {"q5": (q5_plan(), q5_inputs(tabs, dates5)),
             "q3": (q3_plan(), q3_inputs(sales, dates3, items))}

    solo = PlanExecutor(mode="eager")
    refs = {q: solo.execute(p, i).table.to_pydict()
            for q, (p, i) in plans.items()}

    inj = faultinj.install(CONFIG)
    health = DeviceHealthMonitor(cooldown_s=0)
    ex = PlanExecutor(mode="eager", health=health)
    plans_per_session = 3
    p99_bound_ms = 60_000.0
    try:
        with ServingScheduler(ex, workers=3) as sched:
            handles = [sched.open_session(
                f"tenant-{i}",
                priority=("interactive" if i % 2 == 0 else "batch"),
                weight=1.0 + (i % 3),
                # quota sized for the certifier's sound (cross-product
                # loose) join bounds: quota REJECTION is a separate
                # assertion surface (tests/test_serving.py), the soak
                # measures fairness under admitted load
                quota_bytes=1 << 50) for i in range(n_sessions)]
            tickets = []
            for i, h in enumerate(handles):
                qs = ("q3", "q5", "q3") if i % 2 == 0 else \
                    ("q5", "q3", "q5")
                for q in qs[:plans_per_session]:
                    plan, inputs = plans[q]
                    tickets.append((h.id, q, h.submit(plan, inputs)))
            per_session = {}
            degraded = 0
            for sid, q, tk in tickets:
                res = tk.result(timeout=600)
                if res.table.to_pydict() != refs[q]:
                    raise SystemExit(
                        f"serving soak: parity MISS for {sid}/{q} "
                        f"(degraded={res.degraded}, cached={res.cached})")
                degraded += int(res.degraded)
                per_session.setdefault(sid, []).append(res)
            faults = inj.get_and_reset_injected()
            m = sched.metrics()
            waits = []
            for sid, s in m["sessions"].items():
                if s["failed"] or s["completed"] != plans_per_session:
                    raise SystemExit(f"serving soak: session {sid} "
                                     f"starved or failed: {s}")
                waits.append(s["queue_wait_ms"]["p99"])
            p99 = max(waits)
            if p99 > p99_bound_ms:
                raise SystemExit(f"serving soak: p99 queue wait {p99:.0f} "
                                 f"ms exceeds the {p99_bound_ms:.0f} ms "
                                 "bound — a session starved")
            if faults == 0 or degraded == 0:
                raise SystemExit(f"serving soak ineffective: {faults} "
                                 f"faults, {degraded} degraded — the "
                                 "chaos config injected nothing worth "
                                 "recovering from")
            # recovery INSIDE the serving context (legacy stage 3): stop
            # injecting, reset + half-open probe, then the device tier
            # serves. FRESH inputs (new digest) force a cache MISS so
            # this proves real device dispatch — a pre-fatal device-tier
            # completion may sit in the cache, and a hit would pass this
            # check without ever touching the recovered device
            faultinj.uninstall()
            health.reset_device()
            s3, d3, i3 = q3_tables(max(512, n // 4), seed=77)
            fresh = (q3_plan(), q3_inputs(s3, d3, i3))
            fresh_ref = solo.execute(*fresh).table.to_pydict()
            rec = handles[0].run(*fresh, timeout=600)
            if rec.cached or rec.degraded or \
                    rec.table.to_pydict() != fresh_ref:
                raise SystemExit("serving soak: device tier failed to "
                                 "recover after reset_device "
                                 f"(degraded={rec.degraded}, "
                                 f"cached={rec.cached})")
            hot = handles[1].run(*fresh, timeout=600)
            if not hot.cached or hot.degraded or \
                    hot.table.to_pydict() != fresh_ref:
                raise SystemExit("serving soak: the result cache served "
                                 "no parity-checked device-tier hit "
                                 f"after recovery (cached={hot.cached})")
            m = sched.metrics()          # refresh: include recovery runs
            cache_hits = m["cache"]["hits"]
            for sid, s in sorted(m["sessions"].items()):
                last = per_session[sid][-1]
                emit_record(
                    "chaos_soak_serving",
                    {"sessions": n_sessions, "rows": n,
                     "priority": s["priority"], "weight": s["weight"]},
                    s["queue_wait_ms"]["mean"] or 1e-3, n,
                    impl="serving_eager", session=sid,
                    queue_wait_ms=s["queue_wait_ms"]["p99"],
                    cache_hit=s["cache_hits"] > 0,
                    kernels=kernels_of(last),
                    retries=s["retries"], degraded=s["degraded"] > 0,
                    faults_injected=faults,
                    breaker=m["breaker"])
    finally:
        faultinj.uninstall()        # idempotent; recovery already uninstalled
    print(f"serving soak OK: {n_sessions} sessions x {plans_per_session} "
          f"plans, {faults} faults injected, {degraded} degraded, "
          f"{cache_hits} cache hits served, p99 queue wait {p99:.1f} ms, "
          "breaker recovered")


def main(argv=None):
    args = parse_args(argv)
    if args.sessions > 0:
        return soak_serving(args)
    from spark_rapids_tpu import faultinj
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.runtime.health import HALF_OPEN
    from benchmarks.bench_nds_q3 import build_tables as q3_tables
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,
                                      q5_inputs, q5_plan)

    n = max(2000, int(30_000 * args.scale))
    sales, dates3, items = q3_tables(n, seed=7)
    tabs, dates5 = q5_tables(n, seed=3)
    plans = {"q5": (q5_plan(), q5_inputs(tabs, dates5)),
             "q3": (q3_plan(), q3_inputs(sales, dates3, items))}

    # fault-free references (and compile warm-up) before the injector loads
    ex = PlanExecutor(mode="eager")
    refs = {q: ex.execute(p, i).table.to_pydict()
            for q, (p, i) in plans.items()}

    inj = faultinj.install(CONFIG)
    totals = {"retries": 0, "faults": 0, "degraded": 0}
    try:
        def soak(q, expect_degraded=None):
            plan, inputs = plans[q]
            res, ms = _run(ex, plan, inputs)
            faults = inj.get_and_reset_injected()
            if res.table.to_pydict() != refs[q]:
                raise SystemExit(f"chaos soak: {q} parity MISS "
                                 f"(degraded={res.degraded})")
            if expect_degraded is not None and res.degraded != expect_degraded:
                raise SystemExit(f"chaos soak: {q} degraded={res.degraded}, "
                                 f"expected {expect_degraded} "
                                 f"(breaker {res.breaker})")
            totals["retries"] += res.retries
            totals["faults"] += faults
            totals["degraded"] += int(res.degraded)
            emit_record("chaos_soak", {"query": q, "rows": n}, ms, n,
                        impl="plan_eager", retries=res.retries,
                        kernels=kernels_of(res),
                        faults_injected=faults, degraded=res.degraded,
                        breaker=res.breaker["state"])
            return res

        # 1. nonfatal storm + the one fatal (first plan.Sort): degrades
        soak("q5", expect_degraded=True)
        # 2. breaker open, device poisoned: full plans stay on the CPU tier
        soak("q3", expect_degraded=True)
        # 3. operator intervention: reset + half-open probe -> normal tier
        ex.health.reset_device()
        assert ex.health.breaker.state == HALF_OPEN
        res = soak("q3", expect_degraded=False)
        if res.breaker["state"] != "closed":
            raise SystemExit(f"chaos soak: breaker failed to close after "
                             f"reset_device ({res.breaker})")
    finally:
        faultinj.uninstall()

    health = ex.health.get_and_reset_metrics()
    if totals["faults"] == 0 or totals["retries"] == 0 \
            or totals["degraded"] == 0:
        raise SystemExit(f"chaos soak ineffective: {totals} (health "
                         f"counters {health}) — fault config injected "
                         "nothing worth recovering from")
    print(f"chaos soak OK: {totals['faults']} faults injected, "
          f"{totals['retries']} retries, {totals['degraded']} degraded "
          f"completions, breaker closed")


if __name__ == "__main__":
    main()
