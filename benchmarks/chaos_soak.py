"""Chaos soak: the NDS plan pipelines under a seeded fault-injection config.

The nightly robustness gate (ci/nightly.sh): run q5 and q3 through the plan
engine while `configs/chaos_soak.json` injects a mix of nonfatal faults
(device asserts on joins/aggregates, a substituted return code on projects)
plus ONE fatal fault armed on the first `plan.Sort` interception — and
assert the production recovery story end to end:

1. q5 absorbs the nonfatal faults as backoff-paced retries, then hits the
   fatal at its final Sort: the breaker trips and the plan COMPLETES on the
   degraded CPU tier with result parity against the fault-free run.
2. q3 starts with the breaker open (device quarantined, still poisoned):
   it runs fully degraded without touching the device — parity again.
3. `reset_device()` arms the half-open probation; the heartbeat probe
   closes the breaker and q3 re-runs on the normal path — parity again.

Every run emits a bench JSONL row with the robustness fields (`retries`,
`faults_injected`, `degraded` — benchmarks/common.py emit_record), so the
nightly log shows how much chaos the engine actually absorbed. The soak
FAILS (non-zero exit) on any parity miss, on zero injected faults, zero
retries, or zero degraded completions — a silently-ineffective fault config
must not pass as green.

Multi-session serving soak (`--sessions N`, docs/serving.md): the same
chaos config hammers N concurrent tenant sessions submitting a mixed
q3/q5 workload through `serving.ServingScheduler` — the realistic
mixed-workload load test the ROADMAP promised this harness would become.
Asserts: bit-exact result parity against the fault-free solo run for
EVERY session's EVERY completion (device, degraded, or cached), zero
failed/starved sessions with a bounded p99 queue wait, >= 1
parity-checked device-tier cache hit scheduler-wide after recovery
(degraded results never cache, so most chaos-phase sessions legitimately
finish hit-less), and the same injected-chaos effectiveness floor as the
legacy mode. Emits one JSONL row per session with the serving stamps
(`session`, `queue_wait_ms`, `cache_hit` — lint_metrics-enforced).

Fleet soak (`--workers N` with `--sessions`, docs/serving.md#fleet): the
same chaos storm through `serving.FleetScheduler` — N executor workers
behind the router, one worker KILLED mid-storm while it holds in-flight
work. Asserts: zero failed sessions (every ticket resolves — queued work
on the dead worker replays on survivors), bit-exact per-session parity
vs solo for every completion, a bounded p99 queue wait, and >= 1
parity-checked cache hit SERVED by a different worker than the one that
COMPUTED it (the consistent-hash locality + promotion proof). Each
session's JSONL row carries the `worker_id` stamp alongside the serving
stamps (lint_metrics-enforced for fleet-path rows).

Self-healing phase (appended to the fleet soak, docs/serving.md#fleet-
self-healing): a SECOND fleet comes up with auto-respawn, hot
replication, the health sweep, and quarantine=degrade armed, under
`SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S=0` so breaker trips stick OPEN.
One worker is KILLED mid-storm and a poison plan (its device-tier
executions trip the worker's breaker) gets two more workers REAPED by
the sweep — and the phase asserts the full healing loop: the fleet
returns to N workers (respawns), the poison fingerprint is quarantined
after its second distinct-worker trip and never trips a third, the
killed worker's hot fingerprint survives as a REPLICA cache hit on its
ring successor, a once-run fingerprint re-executes on the rehomed
worker with gossiped observed stats (`charge_source == "observed"`,
`attempts == 1`), a graceful drain returns to N again, and ZERO
sessions fail through all of it.

Lockdep-armed soak (SPARK_RAPIDS_TPU_LOCKDEP=1, any mode): every
engine lock is constructed through the runtime lock-order witness
(runtime/lockdep.py), rows stamp `lockdep_edges`/`lockdep_cycles`, and
the soak FAILS on any observed lock-order cycle or any dynamic edge
missing from tools/lint_concurrency.py's static graph — the nightly's
empirical audit of the linter's interprocedural resolution
(docs/analysis.md#concurrency-invariants).
"""
import os
import sys
import time

# keep retry pacing out of the nightly wall-clock (config reads at use time)
os.environ.setdefault("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_BASE_MS", "1")
os.environ.setdefault("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_MAX_MS", "8")

sys.path.insert(0, ".")

# Lock-order witness (runtime/lockdep.py, docs/analysis.md#concurrency-
# invariants): when the nightly arms SPARK_RAPIDS_TPU_LOCKDEP=1, the
# tracing factories must be installed BEFORE the engine — or
# benchmarks.common, which pulls it in — is imported, so module-level
# locks are constructed wrapped. The env var is read directly because
# importing config would import the engine first; the knob is latched
# here at install time.
_LOCKDEP = None
if os.environ.get("SPARK_RAPIDS_TPU_LOCKDEP", "0").lower() \
        not in ("0", "", "off"):
    import importlib.util as _ilu
    _root = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         os.pardir))
    _spec = _ilu.spec_from_file_location(
        "spark_rapids_tpu.runtime.lockdep",
        os.path.join(_root, "spark_rapids_tpu", "runtime", "lockdep.py"))
    _LOCKDEP = _ilu.module_from_spec(_spec)
    sys.modules[_spec.name] = _LOCKDEP
    _spec.loader.exec_module(_LOCKDEP)
    _LOCKDEP.install()

from benchmarks.common import emit_record, parse_args  # noqa: E402


def _lockdep_stats():
    """(edge classes, cycles) the witness observed so far, or
    (None, None) unarmed — emit_record omits None fields."""
    if _LOCKDEP is None:
        return None, None
    snap = _LOCKDEP.snapshot()
    return len(snap["edges"]), len(snap["cycles"])


def _lockdep_certify():
    """Armed-soak verdict: any observed lock-order cycle, or any
    dynamic edge the static linter (tools/lint_concurrency.py) failed
    to predict, fails the soak even though every result had parity."""
    if _LOCKDEP is None:
        return
    rep = _LOCKDEP.certify()
    print(f"lockdep: {rep['observed']} observed edge class(es): "
          f"{len(rep['mapped'])} mapped to the static graph, "
          f"{len(rep['missing'])} missing from it, "
          f"{len(rep['unmapped'])} at unmodeled sites; "
          f"{len(rep['cycles'])} cycle(s)")
    if not rep["ok"]:
        for m in rep["missing"]:
            print(f"lockdep: dynamic edge NOT in static graph: {m}")
        for c in rep["cycles"]:
            print(f"lockdep: observed lock-order cycle: {c}")
        raise SystemExit("lockdep: the armed soak observed a lock-order "
                         "cycle or an edge the static linter missed")
    if rep["observed"] == 0:
        # the fleet/serving paths provably nest locks; observing none
        # means the witness never traced (an install-ordering or path-
        # normalization regression) — same rule as zero injected faults
        raise SystemExit("lockdep ineffective: the armed soak observed "
                         "ZERO lock-order edges — the witness is not "
                         "actually tracing")

CONFIG = os.path.join(os.path.dirname(__file__), os.pardir, "configs",
                      "chaos_soak.json")


def _run(ex, plan, inputs):
    t0 = time.perf_counter()
    res = ex.execute(plan, inputs)
    return res, (time.perf_counter() - t0) * 1e3


def soak_serving(args):
    """`--sessions N` mode: N tenants through the serving scheduler under
    the seeded chaos config (module docstring, docs/serving.md)."""
    from spark_rapids_tpu import faultinj
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.runtime.health import DeviceHealthMonitor
    from spark_rapids_tpu.serving import ServingScheduler
    from benchmarks.bench_nds_q3 import build_tables as q3_tables
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,
                                      q5_inputs, q5_plan)

    n_sessions = args.sessions
    n = max(2000, int(30_000 * args.scale))
    sales, dates3, items = q3_tables(n, seed=7)
    tabs, dates5 = q5_tables(n, seed=3)
    plans = {"q5": (q5_plan(), q5_inputs(tabs, dates5)),
             "q3": (q3_plan(), q3_inputs(sales, dates3, items))}

    solo = PlanExecutor(mode="eager")
    refs = {q: solo.execute(p, i).table.to_pydict()
            for q, (p, i) in plans.items()}

    inj = faultinj.install(CONFIG)
    health = DeviceHealthMonitor(cooldown_s=0)
    ex = PlanExecutor(mode="eager", health=health)
    plans_per_session = 3
    p99_bound_ms = 60_000.0
    try:
        with ServingScheduler(ex, workers=3) as sched:
            handles = [sched.open_session(
                f"tenant-{i}",
                priority=("interactive" if i % 2 == 0 else "batch"),
                weight=1.0 + (i % 3),
                # quota sized for the certifier's sound (cross-product
                # loose) join bounds: quota REJECTION is a separate
                # assertion surface (tests/test_serving.py), the soak
                # measures fairness under admitted load
                quota_bytes=1 << 50) for i in range(n_sessions)]
            tickets = []
            for i, h in enumerate(handles):
                qs = ("q3", "q5", "q3") if i % 2 == 0 else \
                    ("q5", "q3", "q5")
                for q in qs[:plans_per_session]:
                    plan, inputs = plans[q]
                    tickets.append((h.id, q, h.submit(plan, inputs)))
            per_session = {}
            degraded = 0
            for sid, q, tk in tickets:
                res = tk.result(timeout=600)
                if res.table.to_pydict() != refs[q]:
                    raise SystemExit(
                        f"serving soak: parity MISS for {sid}/{q} "
                        f"(degraded={res.degraded}, cached={res.cached})")
                degraded += int(res.degraded)
                per_session.setdefault(sid, []).append(res)
            faults = inj.get_and_reset_injected()
            m = sched.metrics()
            waits = []
            for sid, s in m["sessions"].items():
                if s["failed"] or s["completed"] != plans_per_session:
                    raise SystemExit(f"serving soak: session {sid} "
                                     f"starved or failed: {s}")
                waits.append(s["queue_wait_ms"]["p99"])
            p99 = max(waits)
            if p99 > p99_bound_ms:
                raise SystemExit(f"serving soak: p99 queue wait {p99:.0f} "
                                 f"ms exceeds the {p99_bound_ms:.0f} ms "
                                 "bound — a session starved")
            if faults == 0 or degraded == 0:
                raise SystemExit(f"serving soak ineffective: {faults} "
                                 f"faults, {degraded} degraded — the "
                                 "chaos config injected nothing worth "
                                 "recovering from")
            # recovery INSIDE the serving context (legacy stage 3): stop
            # injecting, reset + half-open probe, then the device tier
            # serves. FRESH inputs (new digest) force a cache MISS so
            # this proves real device dispatch — a pre-fatal device-tier
            # completion may sit in the cache, and a hit would pass this
            # check without ever touching the recovered device
            faultinj.uninstall()
            health.reset_device()
            s3, d3, i3 = q3_tables(max(512, n // 4), seed=77)
            fresh = (q3_plan(), q3_inputs(s3, d3, i3))
            fresh_ref = solo.execute(*fresh).table.to_pydict()
            rec = handles[0].run(*fresh, timeout=600)
            if rec.cached or rec.degraded or \
                    rec.table.to_pydict() != fresh_ref:
                raise SystemExit("serving soak: device tier failed to "
                                 "recover after reset_device "
                                 f"(degraded={rec.degraded}, "
                                 f"cached={rec.cached})")
            hot = handles[1].run(*fresh, timeout=600)
            if not hot.cached or hot.degraded or \
                    hot.table.to_pydict() != fresh_ref:
                raise SystemExit("serving soak: the result cache served "
                                 "no parity-checked device-tier hit "
                                 f"after recovery (cached={hot.cached})")
            m = sched.metrics()          # refresh: include recovery runs
            cache_hits = m["cache"]["hits"]
            ld_edges, ld_cycles = _lockdep_stats()
            for sid, s in sorted(m["sessions"].items()):
                last = per_session[sid][-1]
                emit_record(
                    "chaos_soak_serving",
                    {"sessions": n_sessions, "rows": n,
                     "priority": s["priority"], "weight": s["weight"]},
                    s["queue_wait_ms"]["mean"] or 1e-3, n,
                    impl="serving_eager", session=sid,
                    queue_wait_ms=s["queue_wait_ms"]["p99"],
                    cache_hit=s["cache_hits"] > 0,
                    kernels=kernels_of(last),
                    retries=s["retries"], degraded=s["degraded"] > 0,
                    faults_injected=faults,
                    lockdep_edges=ld_edges, lockdep_cycles=ld_cycles,
                    breaker=m["breaker"])
    finally:
        faultinj.uninstall()        # idempotent; recovery already uninstalled
    _lockdep_certify()
    print(f"serving soak OK: {n_sessions} sessions x {plans_per_session} "
          f"plans, {faults} faults injected, {degraded} degraded, "
          f"{cache_hits} cache hits served, p99 queue wait {p99:.1f} ms, "
          "breaker recovered")


def _wrap_poison(fleet, poison_fp, tripped):
    """Arm the poison plan on every (not-yet-wrapped) worker: a
    device-tier execution of `poison_fp` trips that worker's breaker —
    attributed, because the dispatcher's attribution scope is already
    installed — and completes on the CPU tier so the TICKET still
    resolves (the worker dies, the tenant must not). Deterministic
    per-worker failure modeling: faultinj poisons the process-global
    device, which thread-mode fleet workers share, so it cannot model
    'this plan kills whichever worker runs it'."""
    for w in fleet._workers.values():
        if not w.alive or getattr(w.executor, "_soak_poisoned", False):
            continue
        w.executor._soak_poisoned = True

        def _mk(orig, w=w):
            def execute(plan, inputs=None, **kw):
                if plan.fingerprint == poison_fp \
                        and kw.get("tier") != "cpu":
                    w.health.trip("fatal",
                                  RuntimeError("soak poison plan"))
                    tripped.append(w.id)
                    kw = dict(kw, tier="cpu")
                return orig(plan, inputs, **kw)
            return execute
        w.executor.execute = _mk(w.executor.execute)


def _soak_selfheal(args, solo):
    """Self-healing phase (module docstring): kill + poison-reap storm
    against a respawn-enabled fleet; returns the emit_record fields."""
    from spark_rapids_tpu.serving import FleetScheduler
    from benchmarks.nds_plans import kernels_of
    import numpy as _np
    import jax.numpy as _jnp
    from spark_rapids_tpu import Column, Table, dtypes
    from spark_rapids_tpu.plan import PlanBuilder, col

    n_workers = max(3, args.workers)

    def _plan(thr):
        b = PlanBuilder()
        return (b.scan("t", schema=["k", "v"])
                .filter(col("v") > thr)
                .aggregate(["k"], [("v", "sum", "total")])
                .sort(["k"]).build())

    def _tab(seed, rows=10_000):
        rng = _np.random.default_rng(seed)
        return Table(
            [Column(dtype=dtypes.INT64, length=rows,
                    data=_jnp.asarray(rng.integers(
                        0, hi, rows, dtype=_np.int64)))
             for hi in (50, 200)], names=["k", "v"])

    warm_tab = _tab(11)
    prev_cd = os.environ.get("SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S")
    # cooldown 0: a tripped breaker stays OPEN (no self-arming
    # half-open), which is exactly the stuck state reap_unhealthy and
    # the sweep exist for — trips become reaps become respawns
    os.environ["SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S"] = "0"
    try:
        with FleetScheduler(workers=n_workers, respawn=True,
                            respawn_max=16, respawn_backoff_ms=1,
                            quarantine="degrade", hot_replicas=1,
                            hot_k=8, sweep_ms=25) as fleet:
            # two plans sharing a ring home (scan thresholds until two
            # collide): ONE kill then proves both warm stories — the
            # twice-run plan survives as a replica hit, the once-run
            # plan re-executes warm off gossiped stats
            hot_plan = _plan(0)
            home0 = fleet._ring.route(hot_plan.fingerprint)
            once_plan = next(
                p for p in (_plan(t) for t in range(1, 200))
                if fleet._ring.route(p.fingerprint) == home0)
            poison_plan = next(
                p for p in (_plan(t) for t in range(200, 400))
                if p.fingerprint not in (hot_plan.fingerprint,
                                         once_plan.fingerprint))
            refs = {p.fingerprint: solo.execute(
                p, {"t": warm_tab}).table.to_pydict()
                for p in (hot_plan, once_plan, poison_plan)}

            def _check(res, plan):
                if res.table.to_pydict() != refs[plan.fingerprint]:
                    raise SystemExit("self-heal soak: parity MISS")
                return res

            sA = fleet.open_session("healer", quota_bytes=1 << 50)
            # warm round: hot_plan runs TWICE (>= 2 runs + top-K ->
            # replicated to its ring successor), once_plan runs once
            # (observed stats on home0 only — until gossip)
            _check(sA.run(hot_plan, {"t": warm_tab}), hot_plan)
            _check(sA.run(hot_plan, {"t": warm_tab}), hot_plan)
            _check(sA.run(once_plan, {"t": warm_tab}), once_plan)
            if fleet.metrics()["replications"] < 1:
                raise SystemExit("self-heal soak: hot fingerprint was "
                                 "not replicated after its second run")
            # light storm riding through the healing events
            sB = fleet.open_session("storm-b", quota_bytes=1 << 50)
            sC = fleet.open_session("storm-c", quota_bytes=1 << 50)
            storm = []
            for t in range(100, 106):
                p = _plan(t)
                refs[p.fingerprint] = solo.execute(
                    p, {"t": warm_tab}).table.to_pydict()
                storm.append((p, sB.submit(p, {"t": warm_tab})))
                storm.append((p, sC.submit(p, {"t": warm_tab})))

            def _await_heal(deadline_s=30.0, dead=()):
                t_end = time.monotonic() + deadline_s
                while time.monotonic() < t_end:
                    with fleet._lock:
                        routable = [w.id for w
                                    in fleet._routable_locked()]
                    if len(routable) >= n_workers and \
                            not (set(dead) & set(routable)):
                        return routable
                    time.sleep(0.02)
                raise SystemExit(
                    f"self-heal soak: fleet did not heal back to "
                    f"{n_workers} workers (routable={routable}, "
                    f"dead={list(dead)})")

            # KILL mid-storm: home0 dies holding the warm state
            fleet.kill_worker(home0)
            _await_heal(dead=[home0])
            # warm proof 1 — replica hit: the ring rehomes hot_plan to
            # exactly the successor the replica was pushed to
            tk = sA.submit(hot_plan, {"t": warm_tab})
            res = _check(tk.result(timeout=600), hot_plan)
            if not tk.cached or tk.worker == home0:
                raise SystemExit(
                    "self-heal soak: hot fingerprint did not survive "
                    f"its home's death as a replica hit (cached="
                    f"{tk.cached}, worker={tk.worker})")
            # warm proof 2 — gossip: once_plan re-executes on the
            # rehomed worker, but the kill gossiped home0's observed
            # stats to every survivor: admission charges observed
            # bytes (not certified bounds) and compilation is ONE shot
            tk = sA.submit(once_plan, {"t": warm_tab})
            res = _check(tk.result(timeout=600), once_plan)
            if tk.charge_source != "observed" or res.attempts != 1:
                raise SystemExit(
                    "self-heal soak: rehomed fingerprint was not warm "
                    f"(charge_source={tk.charge_source}, "
                    f"attempts={res.attempts})")
            # POISON storm: device-tier executions of poison_plan trip
            # whichever worker runs them; cooldown 0 pins the breaker
            # OPEN, the sweep reaps, respawn replaces. Fresh inputs per
            # submission (new digest) so no cache hit short-circuits
            # the trip. After TWO distinct worker incarnations trip,
            # the fingerprint is quarantined — the third submission is
            # CPU-pinned (degrade policy) and trips NOBODY.
            tripped = []
            _wrap_poison(fleet, poison_plan.fingerprint, tripped)
            for round_i, seed in enumerate((21, 22)):
                ptab = _tab(seed)
                pref = solo.execute(
                    poison_plan, {"t": ptab}).table.to_pydict()
                ptk = sA.submit(poison_plan, {"t": ptab})
                if ptk.result(timeout=600).table.to_pydict() != pref:
                    raise SystemExit("self-heal soak: poison parity "
                                     f"MISS (round {round_i})")
                _await_heal(dead=tripped)
                _wrap_poison(fleet, poison_plan.fingerprint, tripped)
            if len(set(tripped)) != 2:
                raise SystemExit(
                    f"self-heal soak: expected trips on exactly 2 "
                    f"distinct workers, got {tripped}")
            if poison_plan.fingerprint not in fleet.quarantined():
                raise SystemExit("self-heal soak: poison fingerprint "
                                 "not quarantined after 2 distinct "
                                 "worker trips")
            ptab = _tab(23)
            pref = solo.execute(
                poison_plan, {"t": ptab}).table.to_pydict()
            ptk = sA.submit(poison_plan, {"t": ptab})
            if ptk.result(timeout=600).table.to_pydict() != pref:
                raise SystemExit("self-heal soak: quarantined plan "
                                 "lost parity on the CPU pin")
            if len(tripped) != 2:
                raise SystemExit(
                    "self-heal soak: a QUARANTINED fingerprint tripped "
                    f"a third breaker ({tripped}) — quarantine is not "
                    "containing the crash amplifier")
            # graceful drain: in-flight work finishes, fleet heals back
            with fleet._lock:
                drainee = fleet._routable_locked()[0].id
            fleet.drain_worker(drainee, timeout=120)
            routable = _await_heal(dead=[drainee])
            # the storm rode through kill/reap/drain: every ticket
            # resolves with parity, no session fails
            for p, tk in storm:
                if tk.result(
                        timeout=600).table.to_pydict() != \
                        refs[p.fingerprint]:
                    raise SystemExit("self-heal soak: storm parity "
                                     "MISS across healing events")
            fm = fleet.metrics()
            failed = sum(
                s["failed"]
                for wd in fm["workers"].values() if wd["serving"]
                for s in wd["serving"]["sessions"].values())
            if failed:
                raise SystemExit(f"self-heal soak: {failed} session "
                                 "failures — healing dropped work")
            if fm["killed"] < 1 or fm["reaped"] < 2 or \
                    fm["drained"] < 1 or fm["respawned"] < 4:
                raise SystemExit(
                    "self-heal soak: healing counters did not move "
                    f"(killed={fm['killed']}, reaped={fm['reaped']}, "
                    f"drained={fm['drained']}, "
                    f"respawned={fm['respawned']})")
            ld_edges, ld_cycles = _lockdep_stats()
            emit_record(
                "chaos_soak_fleet_selfheal",
                {"workers": n_workers, "rows": 10_000},
                res.wall_ms or 1e-3, 10_000,
                impl="serving_fleet", session="healer",
                worker_id=tk.worker or routable[0],
                respawns=fm["respawned"],
                replays=fm["replayed_jobs"],
                cache_hit=True, kernels=kernels_of(res),
                degraded=False, retries=0,
                quarantined=len(fm["quarantined"]),
                reaped=fm["reaped"], drained=fm["drained"],
                lockdep_edges=ld_edges, lockdep_cycles=ld_cycles)
            print(f"self-heal soak OK: killed 1 + reaped "
                  f"{fm['reaped']} + drained {fm['drained']}, "
                  f"{fm['respawned']} respawned (fleet back to "
                  f"{len(routable)}), poison quarantined after "
                  f"{len(set(tripped))} distinct-worker trips, "
                  f"replica hit + observed-charge rehome proven, "
                  f"0 failed sessions")
    finally:
        if prev_cd is None:
            os.environ.pop("SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S", None)
        else:
            os.environ["SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S"] = prev_cd


def soak_fleet(args):
    """`--workers N` mode: the chaos storm through the fleet tier with a
    deliberate mid-storm worker kill (module docstring)."""
    from spark_rapids_tpu import faultinj
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.serving import FleetScheduler
    from benchmarks.bench_nds_q3 import build_tables as q3_tables
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,
                                      q5_inputs, q5_plan)

    n_sessions = max(8, args.sessions)
    n_workers = max(2, args.workers)
    n = max(2000, int(30_000 * args.scale))
    sales, dates3, items = q3_tables(n, seed=7)
    tabs, dates5 = q5_tables(n, seed=3)
    plans = {"q5": (q5_plan(), q5_inputs(tabs, dates5)),
             "q3": (q3_plan(), q3_inputs(sales, dates3, items))}

    solo = PlanExecutor(mode="eager")
    refs = {q: solo.execute(p, i).table.to_pydict()
            for q, (p, i) in plans.items()}

    inj = faultinj.install(CONFIG)
    plans_per_session = 3
    p99_bound_ms = 60_000.0
    try:
        with FleetScheduler(workers=n_workers) as fleet:
            handles = [fleet.open_session(
                f"tenant-{i}",
                priority=("interactive" if i % 2 == 0 else "batch"),
                weight=1.0 + (i % 3),
                quota_bytes=1 << 50) for i in range(n_sessions)]
            tickets = []
            for i, h in enumerate(handles):
                qs = ("q3", "q5", "q3") if i % 2 == 0 else \
                    ("q5", "q3", "q5")
                for q in qs[:plans_per_session]:
                    plan, inputs = plans[q]
                    tickets.append((h.id, q, h.submit(plan, inputs)))
            # MID-STORM KILL: a worker currently holding in-flight work,
            # never the last live one — its queued jobs must replay on
            # the survivors with nobody's session failing
            victim = next(
                (tk.worker for _, _, tk in tickets
                 if not tk.done() and tk.worker), None)
            if victim is None:
                raise SystemExit("fleet soak: no in-flight work to kill "
                                 "under — storm too small to prove "
                                 "failover")
            replayed = fleet.kill_worker(victim)
            per_session = {}
            degraded = 0
            for sid, q, tk in tickets:
                res = tk.result(timeout=600)
                if res.table.to_pydict() != refs[q]:
                    raise SystemExit(
                        f"fleet soak: parity MISS for {sid}/{q} on "
                        f"{tk.worker} (degraded={res.degraded}, "
                        f"cached={res.cached}, replays={tk.replays})")
                degraded += int(res.degraded)
                per_session.setdefault(sid, []).append((tk, res))
            faults = inj.get_and_reset_injected()
            if len(per_session) != n_sessions or any(
                    len(v) != plans_per_session
                    for v in per_session.values()):
                raise SystemExit("fleet soak: a session lost completions "
                                 "across the kill")
            waits = {sid: max(tk.queue_wait_ms for tk, _ in v)
                     for sid, v in per_session.items()}
            p99 = max(waits.values())
            if p99 > p99_bound_ms:
                raise SystemExit(f"fleet soak: p99 queue wait {p99:.0f} "
                                 f"ms exceeds the {p99_bound_ms:.0f} ms "
                                 "bound — a session starved")
            if faults == 0 or degraded == 0:
                raise SystemExit(f"fleet soak ineffective: {faults} "
                                 f"faults, {degraded} degraded")
            # recovery + the cross-worker locality proof: stop injecting,
            # reset every survivor's device, then stage a fresh q3 so
            # its COMPUTING worker is not its ring home — a pin plan
            # whose fingerprint homes on a DIFFERENT worker goes first,
            # and session affinity carries the in-flight fresh q3 to the
            # pin's worker. A fresh session's ring-routed submission
            # then serves the promoted hit back at the q3 home.
            faultinj.uninstall()
            live = [w for w in fleet._workers.values() if w.alive]
            for w in live:
                w.health.reset_device()
                # heartbeat probe closes the half-open breaker NOW: a
                # not-closed breaker carries a routing pressure penalty
                # that would divert the locality probe off its ring home
                w.health.probe()
            s3, d3, i3 = q3_tables(max(512, n // 4), seed=77)
            fresh = (q3_plan(), q3_inputs(s3, d3, i3))
            fresh_ref = solo.execute(*fresh).table.to_pydict()
            home = fleet._ring.route(fresh[0].fingerprint)
            import numpy as _np
            from spark_rapids_tpu import Column, Table, dtypes
            from spark_rapids_tpu.plan import PlanBuilder, col

            def _pin_plan(thr):
                b = PlanBuilder()
                return (b.scan("t", schema=["k", "v"])
                        .filter(col("v") > thr)
                        .aggregate(["k"], [("v", "sum", "total")])
                        .sort(["k"]).build())

            pin_plan = next(p for p in (_pin_plan(t) for t in range(100))
                            if fleet._ring.route(p.fingerprint) != home)
            import jax.numpy as _jnp
            rng = _np.random.default_rng(9)
            pin_tab = Table(
                [Column(dtype=dtypes.INT64, length=50_000,
                        data=_jnp.asarray(rng.integers(
                            0, hi, 50_000, dtype=_np.int64)))
                 for hi in (50, 100)], names=["k", "v"])
            h = fleet.open_session("diverter", quota_bytes=1 << 50)
            pin_tk = h.submit(pin_plan, {"t": pin_tab})
            tk = h.submit(*fresh)        # rides affinity off its home
            res = tk.result(timeout=600)
            pin_tk.result(timeout=600)
            if res.table.to_pydict() != fresh_ref:
                raise SystemExit("fleet soak: recovery parity MISS")
            if res.cached or tk.worker == home:
                # the affinity window closed before the fresh submit
                # (pin finished first) and the entry sits AT home, where
                # no cross-worker hit can prove anything: seed a second
                # fresh dataset through a peer worker's own front door
                s3b, d3b, i3b = q3_tables(max(512, n // 4), seed=78)
                fresh = (q3_plan(), q3_inputs(s3b, d3b, i3b))
                fresh_ref = solo.execute(*fresh).table.to_pydict()
                peer = next(w for w in live if w.id != home)
                peer.scheduler.open_session(
                    "seed", quota_bytes=1 << 50).run(*fresh)
            probe = fleet.open_session("prober", quota_bytes=1 << 50)
            tk = probe.submit(*fresh)
            hot = tk.result(timeout=600)
            if not hot.cached or hot.table.to_pydict() != fresh_ref:
                raise SystemExit("fleet soak: no parity-checked cache "
                                 f"hit at the ring home (cached="
                                 f"{hot.cached}, worker={tk.worker})")
            if tk.worker == hot.worker or not hot.worker:
                raise SystemExit(
                    "fleet soak: the hit was not cross-worker (served "
                    f"by {tk.worker}, computed by {hot.worker or '?'}) "
                    "— consistent-hash locality unproven")
            fm = fleet.metrics()
            ld_edges, ld_cycles = _lockdep_stats()
            for sid in sorted(per_session):
                tk_last, res_last = per_session[sid][-1]
                emit_record(
                    "chaos_soak_fleet",
                    {"sessions": n_sessions, "workers": n_workers,
                     "rows": n},
                    waits[sid] or 1e-3, n,
                    impl="serving_fleet", session=sid,
                    worker_id=tk_last.worker,
                    queue_wait_ms=waits[sid],
                    cache_hit=any(r.cached for _, r in per_session[sid]),
                    kernels=kernels_of(res_last),
                    retries=sum(r.retries for _, r in per_session[sid]),
                    degraded=any(r.degraded for _, r in per_session[sid]),
                    faults_injected=faults,
                    lockdep_edges=ld_edges, lockdep_cycles=ld_cycles,
                    replays=sum(t.replays for t, _ in per_session[sid]))
    finally:
        faultinj.uninstall()
    # phase 2: the self-healing storm (kill + poison-reap + drain
    # against a respawn-enabled fleet) — separate fleet, same process,
    # so the lockdep witness certifies BOTH phases' lock traffic
    _soak_selfheal(args, solo)
    _lockdep_certify()
    print(f"fleet soak OK: {n_sessions} sessions x {plans_per_session} "
          f"plans over {n_workers} workers, killed {victim} mid-storm "
          f"({replayed} jobs replayed, {fm['replayed_jobs']} total), "
          f"{faults} faults, {degraded} degraded, cross-worker hit "
          f"served by {tk.worker} for {hot.worker}'s computation, "
          f"{fm['cache_promotions']} promotions, p99 queue wait "
          f"{p99:.1f} ms")


def main(argv=None):
    args = parse_args(argv)
    if args.workers > 0:
        return soak_fleet(args)
    if args.sessions > 0:
        return soak_serving(args)
    from spark_rapids_tpu import faultinj
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.runtime.health import HALF_OPEN
    from benchmarks.bench_nds_q3 import build_tables as q3_tables
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.nds_plans import (kernels_of, q3_inputs, q3_plan,
                                      q5_inputs, q5_plan)

    n = max(2000, int(30_000 * args.scale))
    sales, dates3, items = q3_tables(n, seed=7)
    tabs, dates5 = q5_tables(n, seed=3)
    plans = {"q5": (q5_plan(), q5_inputs(tabs, dates5)),
             "q3": (q3_plan(), q3_inputs(sales, dates3, items))}

    # fault-free references (and compile warm-up) before the injector loads
    ex = PlanExecutor(mode="eager")
    refs = {q: ex.execute(p, i).table.to_pydict()
            for q, (p, i) in plans.items()}

    inj = faultinj.install(CONFIG)
    totals = {"retries": 0, "faults": 0, "degraded": 0}
    try:
        def soak(q, expect_degraded=None):
            plan, inputs = plans[q]
            res, ms = _run(ex, plan, inputs)
            faults = inj.get_and_reset_injected()
            if res.table.to_pydict() != refs[q]:
                raise SystemExit(f"chaos soak: {q} parity MISS "
                                 f"(degraded={res.degraded})")
            if expect_degraded is not None and res.degraded != expect_degraded:
                raise SystemExit(f"chaos soak: {q} degraded={res.degraded}, "
                                 f"expected {expect_degraded} "
                                 f"(breaker {res.breaker})")
            totals["retries"] += res.retries
            totals["faults"] += faults
            totals["degraded"] += int(res.degraded)
            ld_edges, ld_cycles = _lockdep_stats()
            emit_record("chaos_soak", {"query": q, "rows": n}, ms, n,
                        impl="plan_eager", retries=res.retries,
                        kernels=kernels_of(res),
                        faults_injected=faults, degraded=res.degraded,
                        lockdep_edges=ld_edges, lockdep_cycles=ld_cycles,
                        breaker=res.breaker["state"])
            return res

        # 1. nonfatal storm + the one fatal (first plan.Sort): degrades
        soak("q5", expect_degraded=True)
        # 2. breaker open, device poisoned: full plans stay on the CPU tier
        soak("q3", expect_degraded=True)
        # 3. operator intervention: reset + half-open probe -> normal tier
        ex.health.reset_device()
        assert ex.health.breaker.state == HALF_OPEN
        res = soak("q3", expect_degraded=False)
        if res.breaker["state"] != "closed":
            raise SystemExit(f"chaos soak: breaker failed to close after "
                             f"reset_device ({res.breaker})")
    finally:
        faultinj.uninstall()

    health = ex.health.get_and_reset_metrics()
    if totals["faults"] == 0 or totals["retries"] == 0 \
            or totals["degraded"] == 0:
        raise SystemExit(f"chaos soak ineffective: {totals} (health "
                         f"counters {health}) — fault config injected "
                         "nothing worth recovering from")
    _lockdep_certify()
    print(f"chaos soak OK: {totals['faults']} faults injected, "
          f"{totals['retries']} retries, {totals['degraded']} degraded "
          f"completions, breaker closed")


if __name__ == "__main__":
    main()
