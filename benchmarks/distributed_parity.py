"""Nightly distributed-parity stage (ci/nightly.sh, docs/distributed.md).

Runs NDS q5 and q72 through the full-plan SPMD distributed tier on a
>=4-device simulated CPU mesh (benchmarks/nds_plans.run_plan_distributed —
the same helper the bench_nds_q*.py `*_dist` configs use), asserting:

- EXACT result parity per query against the single-device eager tier
  (scan -> join -> agg -> sort all on the mesh, one gather at the sink);
- the optimizer's exchange_planning selected at least one BROADCAST join
  (est_rows-driven small build side: q72's dimension joins, q5's date
  window) and at least one hash-SHUFFLE join (the large-large cs ⋈ inv),
  both verified on the EXECUTED plan's Exchange children;
- a single sink gather and nonzero exchange-bytes on the JSONL rows.

Emits one JSONL row per query with `n_devices`/`mesh_axis`/
`exchange_bytes` plus planned/observed exchange kinds and elision counts,
so the BENCH history tracks the distributed trajectory across revisions.

Runs with the per-fingerprint stats store SCOPED OFF (plan/stats.py):
this gate asserts the STATIC exchange planner's broadcast+shuffle mix —
coverage of both distributed join paths. With adaptivity live, the
single-device reference run feeds observed (post-filter, tiny) build
sides to the distributed run's planner, which then legitimately
broadcasts every join — correct behavior, but it would silently drop the
shuffle path from this gate's coverage. Adaptive exchange decisions get
their own gate in benchmarks/adaptive_bench.py (docs/adaptive.md), and
the JSONL rows here stamp `adaptive: false` so the history can't mix
the two.
"""
import sys

sys.path.insert(0, ".")

import os  # noqa: E402

# the mesh needs simulated devices BEFORE jax initializes a backend
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        (flags + " --xla_force_host_platform_device_count=8").strip()

from benchmarks.common import parse_args                     # noqa: E402
from benchmarks.nds_plans import (dist_mesh, q5_inputs,      # noqa: E402
                                  q5_plan, q72_inputs, q72_plan,
                                  run_plan_distributed)

N_DEVICES = 4


def _join_exchange_kinds(plan):
    """Exchange kinds feeding HashJoin nodes of the EXECUTED plan — the
    selection facts the gate asserts (an aggregate's hash exchange must
    not satisfy the shuffle-JOIN requirement)."""
    from spark_rapids_tpu.plan import Exchange, HashJoin
    kinds = set()
    for node in plan.nodes:
        if isinstance(node, HashJoin):
            for child in node.children:
                if isinstance(child, Exchange):
                    kinds.add(child.how)
    return kinds


def main(argv=None):
    from spark_rapids_tpu.plan import stats as stats_mod
    with stats_mod.scoped_store(None):      # static-planner gate: see
        return _main(argv)                  # module docstring


def _main(argv=None):
    args = parse_args(argv)
    n = max(int(100_000 * args.scale), 10_000)   # keep cs above the
    #                                              broadcast threshold
    iters = min(args.iters, 3)

    from benchmarks.bench_nds_q5 import build_tables as bt5
    from benchmarks.bench_nds_q72 import build_tables as bt72

    mesh = dist_mesh(N_DEVICES)
    assert mesh is not None, \
        f"distributed parity needs >= {N_DEVICES} simulated devices"

    cases = {
        "q5": (q5_plan(), q5_inputs(*bt5(n, seed=3))),
        "q72": (q72_plan(), q72_inputs(*bt72(n, seed=5))),
    }
    join_kinds = set()
    for name, (plan, inputs) in cases.items():
        n_rows = sum(t.num_rows for t in inputs.values())
        rec, res = run_plan_distributed(
            f"distributed_parity_{name}", {"num_rows": n_rows}, plan,
            inputs, n_rows=n_rows, iters=iters, mesh=mesh)
        assert rec["exchange_bytes"] > 0, \
            f"{name}: no exchange bytes recorded"
        # transport honesty (plan/transport.py): both counters present,
        # wire never exceeds logical, and neither is silently zero while
        # the other moves — a pass-through regression (packing quietly
        # disabled, or wire mis-attributed) trips here before it can
        # poison the JSONL trajectory
        assert rec["exchange_bytes_wire"] == rec["exchange_bytes"], name
        assert 0 < rec["exchange_bytes_wire"] <= \
            rec["exchange_bytes_logical"], \
            f"{name}: wire/logical byte counters inconsistent ({rec})"
        assert rec["gathers"] == 1, \
            f"{name}: expected a single sink gather, got {rec['gathers']}"
        assert res.optimizer["exchanges"]["gather"] == 1, name
        join_kinds |= _join_exchange_kinds(res.plan)
    assert "broadcast" in join_kinds, \
        "no broadcast join selected across q5/q72"
    assert "hash" in join_kinds, \
        "no shuffle join selected across q5/q72"
    print("distributed parity OK", flush=True)


if __name__ == "__main__":
    main()
