"""Nightly adaptive-execution gate (ci/nightly.sh, docs/adaptive.md).

Runs NDS q5 and q72 through the capped plan tier COLD then WARM under a
fresh per-fingerprint stats store (spark_rapids_tpu.plan.stats),
asserting the feedback loop's whole contract:

- bit-exact result parity: warm == cold == adaptivity-off (the store may
  change *how* a plan executes, never *what* it returns);
- zero cap-escalation retries on the warm run (`attempts == 1`): the
  observed high-water caps seed a FRESH executor, skipping the geometric
  escalation ladder the cold run paid;
- >= 1 stats-driven optimizer rewrite fired on the warm run: q72's
  inventory join is sized so the static estimate chain keeps the
  authored build side while the OBSERVED post-filter cardinality swaps
  it (`decision_sources` records `swap (observed:<runs>)`);
- warm wall <= cold wall: the warm run pays one compile against the
  cold run's escalation retraces.

Emits one JSONL row per (query, phase in off/cold/warm) via emit_record,
so every row carries the `adaptive`/`stats_hits` stamps alongside
`attempts` and `rules_fired` — the bench history can never silently mix
cold and warm numbers.
"""
import sys

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args        # noqa: E402
from benchmarks.nds_plans import kernels_of                  # noqa: E402
from benchmarks.nds_plans import (q5_inputs, q5_plan,        # noqa: E402
                                  q72_inputs, q72_plan)


def _sliced(table, n):
    """First n rows of a Table (host-side): sizes q72's inventory into
    the window where static estimates keep the authored build side but
    observed cardinalities swap it. Fixed-width non-null columns only
    (all the q72 generator produces) — validity/offsets would need
    slicing too, so refuse rather than mis-slice."""
    import jax.numpy as jnp
    import dataclasses
    from spark_rapids_tpu.columnar import Table
    assert all(c.validity is None and c.offsets is None
               for c in table.columns), \
        "_sliced only handles fixed-width non-null columns"
    cols = [dataclasses.replace(c, length=n, data=jnp.asarray(c.data[:n]))
            for c in table.columns]
    return Table(cols, names=list(table.names))


def _stats_decisions(res):
    """decision_sources entries whose decision consumed OBSERVED
    cardinalities — the 'stats-driven rewrite' evidence."""
    sources = (res.optimizer or {}).get("decision_sources") or {}
    return {k: v for k, v in sources.items() if "observed" in v}


def _run(name, plan, inputs, caps, n_rows):
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.plan import stats as stats_mod

    results, recs = {}, []

    def one(phase, store):
        with stats_mod.scoped_store(store):
            before = 0 if store is None else store.hits
            ex = PlanExecutor(mode="capped", caps=dict(caps))
            res = ex.execute(plan, inputs)
            results[phase] = res.compact().to_pydict()
            rules = (res.optimizer or {}).get("rules_fired")
            recs.append(emit_record(
                f"adaptive_{name}", {"phase": phase}, res.wall_ms, n_rows,
                impl="plan_capped", optimizer="on", rules_fired=rules,
                attempts=res.attempts, kernels=kernels_of(res),
                stats_hits=0 if store is None else store.hits - before,
                adaptive=store is not None,
                stats_decisions=sorted(_stats_decisions(res))))
            return res

    one("off", None)                      # adaptivity disabled outright
    # path="": the cold/warm contract needs a genuinely cold store — it
    # must not inherit SPARK_RAPIDS_TPU_STATS_PATH's persisted state
    store = stats_mod.StatsStore(capacity=32, path="")
    cold = one("cold", store)
    warm = one("warm", store)             # fresh executor: only the STORE
    #                                       carries cold's observations

    assert results["warm"] == results["cold"] == results["off"], \
        f"{name}: adaptivity changed the result"
    assert warm.attempts == 1, \
        (f"{name}: warm run paid {warm.attempts - 1} cap escalation(s) — "
         f"observed-cap seeding failed (caps={warm.caps})")
    assert warm.wall_ms <= cold.wall_ms, \
        (f"{name}: warm wall {warm.wall_ms:.1f} ms exceeded cold "
         f"{cold.wall_ms:.1f} ms")
    assert cold.attempts > 1, \
        (f"{name}: cold run never escalated (attempts="
         f"{cold.attempts}) — the warm zero-escalation assert is vacuous")
    return cold, warm


def main(argv=None):
    args = parse_args(argv)
    # floor at 10k rows (= the shipped --scale 0.1): below this, cold
    # escalation work shrinks until a single fresh-compile of the warm
    # (swapped) plan can exceed it and the strict warm<=cold wall assert
    # measures compile noise instead of the ladder skip — at >=10k the
    # gate has repeatedly shown ~2x headroom
    n = max(int(100_000 * args.scale), 10_000)

    from benchmarks.bench_nds_q5 import build_tables as bt5
    from benchmarks.bench_nds_q72 import build_tables as bt72

    # q5: unions + semi-joins + rollup — exercises cap seeding (the small
    # starting key cap forces a cold escalation ladder: the per-entity
    # aggregates see ~80 distinct entities inside the 14-day date
    # window) and warm wall. No inner joins, so row_cap never engages.
    q5_in = q5_inputs(*bt5(n, seed=3))
    _run("q5", q5_plan(), q5_in, dict(key_cap=16),
         n_rows=sum(t.num_rows for t in q5_in.values()))

    # q72: the deep multi-join. Inventory is sliced so the static
    # estimate chain (filters at 0.5 selectivity) says the probe side is
    # NOT 2x smaller than inventory — build_side keeps — while the
    # observed cardinality after the real hd/date/ship filters is far
    # below inventory — build_side swaps on the warm run, through
    # verify_rewrite. est left ~ 0.5*n vs inv: keep needs inv <= n;
    # observed left ~ 0.1*n: swap needs inv > 0.2*n.
    cs, inv, items, hd, wh, dates = bt72(n, seed=5)
    inv = _sliced(inv, max(min(inv.num_rows, int(0.8 * n)), int(0.3 * n)))
    q72_in = q72_inputs(cs, inv, items, hd, wh, dates)
    _, warm = _run("q72", q72_plan(), q72_in,
                   dict(key_cap=1024, row_cap=1024),
                   n_rows=sum(t.num_rows for t in q72_in.values()))
    decisions = _stats_decisions(warm)
    swaps = {k: v for k, v in decisions.items() if v.startswith("swap")}
    assert swaps and warm.optimizer["rules_fired"].get("build_side"), \
        (f"q72: no stats-driven build-side rewrite fired on the warm run "
         f"(decisions={decisions}, "
         f"rules={warm.optimizer['rules_fired']})")
    assert not warm.optimizer.get("stats_reverted"), \
        "q72: stats-driven rewrite failed verify_rewrite and reverted"
    print("adaptive execution OK", file=sys.stderr)


if __name__ == "__main__":
    main()
