#!/usr/bin/env python
"""Nightly kernel-registry gate (ci/nightly.sh; docs/kernels.md).

Three tiers of assertion, every timing emitted as a JSONL row with the
`kernels` stamp:

1. **Per-kernel parity microbenches** — each registered Pallas kernel
   (fused_select / topk / hash_join) runs FORCED against its XLA fallback
   on a synthetic table and the results must match exactly (on CPU the
   Pallas path runs in interpret mode: semantics, not speed). Timings for
   both paths are recorded so the JSONL history carries per-kernel
   before/after numbers on whatever backend the nightly ran.
2. **NDS capped-tier registry gate** — q5 and q72 run registry-on vs
   forced-fallback through `nds_plans.run_plan_kernels` (exact parity
   asserted inside). On a CPU-only runner the registry must not have
   selected any accelerator (pallas) kernel — auto-selection honors the
   backend — and the run stays parity-green.
3. **Speedup gate (armed on TPU)** — whenever a TPU backend is present,
   the registry-on capped-tier time must beat forced-fallback by
   >= SPEEDUP_MIN on BOTH NDS queries (ROADMAP open item 5's "measurable
   capped-tier speedup on at least two NDS plan queries"). Per the
   cross-cutting rule, device numbers are recorded opportunistically —
   a CPU nightly records, a TPU nightly enforces.
"""
import sys

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

import jax  # noqa: E402

from benchmarks.common import parse_args, run_config  # noqa: E402

SPEEDUP_MIN = 1.02


def _micro_fused_select(iters: int, n: int):
    from spark_rapids_tpu import Column, Table
    from spark_rapids_tpu.ops import apply_boolean_mask, select_pallas
    from spark_rapids_tpu.plan import col

    rng = np.random.default_rng(7)
    t = Table([Column.from_numpy(rng.integers(0, 100, n).astype(np.int32)),
               Column.from_numpy(rng.integers(-5, 5, n).astype(np.int32)),
               Column.from_numpy(
                   rng.integers(-2**40, 2**40, n).astype(np.int64),
                   validity=rng.random(n) > 0.1)],
              names=["a", "b", "v"])
    pred = (col("a") < 10) & (col("b") > 0)
    needed = ["a", "v"]

    def fallback():
        mask = pred.evaluate(t)
        out = apply_boolean_mask(t.select(needed), mask)
        return [c.data for c in out.columns]

    def pallas():
        out = select_pallas.fused_select_compact(t, pred, needed)
        return [c.data for c in out.columns]

    ref = apply_boolean_mask(t.select(needed), pred.evaluate(t))
    got = select_pallas.fused_select_compact(t, pred, needed)
    assert ref.to_pydict() == got.to_pydict(), "fused_select parity broke"
    run_config("kernel_fused_select", {"num_rows": n}, fallback, (),
               n_rows=n, iters=iters, jit=False, kernels="fallback")
    run_config("kernel_fused_select", {"num_rows": n}, pallas, (),
               n_rows=n, iters=iters, jit=False,
               kernels={"fused_select": "pallas"})


def _micro_topk(iters: int, n: int):
    from spark_rapids_tpu import Column, Table
    from spark_rapids_tpu.ops import slice_table, sort_table, topk_pallas

    rng = np.random.default_rng(8)
    t = Table([Column.from_numpy(rng.integers(-10**6, 10**6, n)
                                 .astype(np.int64),
                                 validity=rng.random(n) > 0.05),
               Column.from_numpy(rng.standard_normal(n).astype(np.float32))],
              names=["k", "v"])
    keys, asc, topn = ["k", "v"], [False, True], 50

    def fallback():
        out = slice_table(sort_table(t, key_names=keys, ascending=asc),
                          0, topn)
        return [c.data for c in out.columns]

    def pallas():
        out = topk_pallas.topk_table(t, keys, asc, topn)
        return [c.data for c in out.columns]

    ref = slice_table(sort_table(t, key_names=keys, ascending=asc), 0, topn)
    got = topk_pallas.topk_table(t, keys, asc, topn)
    for rc, gc in zip(ref.columns, got.columns):
        np.testing.assert_array_equal(np.asarray(rc.data),
                                      np.asarray(gc.data))
    run_config("kernel_topk", {"num_rows": n, "k": topn}, fallback, (),
               n_rows=n, iters=iters, jit=False, kernels="fallback")
    run_config("kernel_topk", {"num_rows": n, "k": topn}, pallas, (),
               n_rows=n, iters=iters, jit=False, kernels={"topk": "pallas"})


def _micro_hash_join(iters: int, n: int):
    from spark_rapids_tpu import Column
    from spark_rapids_tpu.ops import inner_join, join_pallas

    rng = np.random.default_rng(9)
    n_build = 400
    lk = [Column.from_numpy(rng.integers(0, 300, n).astype(np.int64),
                            validity=rng.random(n) > 0.05)]
    rk = [Column.from_numpy(rng.integers(0, 300, n_build).astype(np.int64))]

    def fallback():
        lm, rm = inner_join(lk, rk)
        return lm.data, rm.data

    def pallas():
        lm, rm = join_pallas.inner_join_pallas(lk, rk)
        return lm.data, rm.data

    rl, rr = inner_join(lk, rk)
    gl, gr = join_pallas.inner_join_pallas(lk, rk)
    np.testing.assert_array_equal(np.asarray(rl.data), np.asarray(gl.data))
    np.testing.assert_array_equal(np.asarray(rr.data), np.asarray(gr.data))
    run_config("kernel_hash_join", {"probe_rows": n, "build_rows": n_build},
               fallback, (), n_rows=n, iters=iters, jit=False,
               kernels="fallback")
    run_config("kernel_hash_join", {"probe_rows": n, "build_rows": n_build},
               pallas, (), n_rows=n, iters=iters, jit=False,
               kernels={"hash_join": "pallas"})


def main(argv=None):
    args = parse_args(argv)
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # interpret-mode Pallas on CPU is semantics-speed, not device speed —
    # keep the CPU microbench small and honest, scale up on device
    micro_n = max(int(200_000 * args.scale), 4096) if on_tpu else 4096
    _micro_fused_select(args.iters, micro_n)
    _micro_topk(args.iters, micro_n)
    _micro_hash_join(args.iters, micro_n)
    print("# kernel_bench: per-kernel parity OK (pallas forced vs fallback)")

    # ---- NDS capped-tier registry gate -------------------------------------
    from benchmarks.bench_nds_q5 import build_tables as q5_tables
    from benchmarks.bench_nds_q72 import build_tables as q72_tables
    from benchmarks.nds_plans import (q5_inputs, q5_plan, q72_inputs,
                                      q72_plan, run_plan_kernels)

    n_sales = max(int(10_000_000 * args.scale), 8192)
    tabs, dates = q5_tables(n_sales)
    n5 = sum(t.num_rows + r.num_rows for t, r in tabs.values())
    recs5 = run_plan_kernels("nds_q5_pipeline_kernels", {"num_rows": n5},
                             q5_plan(), q5_inputs(tabs, dates),
                             n_rows=n5, iters=args.iters,
                             caps=dict(key_cap=2048))
    t72 = q72_tables(n_sales)
    n72 = t72[0].num_rows
    recs72 = run_plan_kernels(
        "nds_q72_pipeline_kernels", {"num_sales": n72},
        q72_plan(), q72_inputs(*t72), n_rows=n72, iters=args.iters,
        caps=dict(row_cap=max(n72 // 2, 2048), key_cap=max(n72 // 16, 1024)))
    print("# kernel_bench: NDS registry-on vs forced-fallback parity OK")

    by_query = {"q5": recs5, "q72": recs72}
    if not on_tpu:
        # CPU-only runner: auto-selection must not have picked any
        # accelerator kernel (backend-gated registration is the contract)
        for name, (on_rec, _) in by_query.items():
            chosen = on_rec.get("kernels") or {}
            bad = {op: k for op, k in chosen.items() if "pallas" in k}
            assert not bad, \
                f"{name}: pallas selected on a {backend} backend: {bad}"
        print(f"# kernel_bench: registry selected fallbacks everywhere "
              f"on {backend} (gate recorded, not enforced)")
        return
    # TPU present: the capped-tier speedup gate is ARMED (ROADMAP item 5)
    failures = []
    for name, (on_rec, fb_rec) in by_query.items():
        speedup = fb_rec["ms"] / max(on_rec["ms"], 1e-9)
        print(f"# kernel_bench: {name} capped-tier speedup {speedup:.3f}x "
              f"(registry {on_rec['ms']:.3f} ms vs fallback "
              f"{fb_rec['ms']:.3f} ms)")
        if speedup < SPEEDUP_MIN:
            failures.append(f"{name}: {speedup:.3f}x < {SPEEDUP_MIN}x")
    assert not failures, "kernel speedup gate failed: " + "; ".join(failures)
    print("# kernel_bench: TPU speedup gate OK")


if __name__ == "__main__":
    main()
