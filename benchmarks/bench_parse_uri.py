"""parse_uri bench (reference benchmarks/parse_uri.cpp).

Two variants like the reference: random strings (bench_random_parse_uri) and
a valid/garbage/unicode mix swept over a hit_rate axis (bench_parse_uri).
"""
import sys

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (parse_args, run_config,  # noqa: E402
                               strings_column_from_list, uri_mix)


def _random_strings(n_rows, seed=0):
    rng = np.random.default_rng(seed)
    lens = rng.integers(0, 32, size=n_rows)
    alphabet = np.frombuffer(
        b"abcdefghijklmnopqrstuvwxyz0123456789:/?.&=%", dtype=np.uint8)
    return strings_column_from_list(
        [rng.choice(alphabet, size=l).tobytes() for l in lens])


def main(argv=None):
    args = parse_args(argv)
    from spark_rapids_tpu.ops import parse_uri_to_protocol

    n_rows = max(int(1_048_576 * args.scale), 2048)
    col = _random_strings(n_rows, seed=5)
    pad = col.padded_chars()[0].shape[1]   # static bounds -> one jitted program
    run_config("parse_uri_random", {"num_rows": n_rows},
               lambda c: parse_uri_to_protocol(c, pad_to=pad,
                                               out_pad_to=pad).data,
               (col,), n_rows=n_rows, iters=args.iters,
               kernels="fallback")

    for hit_rate in (0, 50, 100):
        col = uri_mix(n_rows, hit_rate, seed=6)
        pad = col.padded_chars()[0].shape[1]
        run_config("parse_uri", {"num_rows": n_rows, "hit_rate": hit_rate},
                   lambda c: parse_uri_to_protocol(c, pad_to=pad,
                                                   out_pad_to=pad).data,
                   (col,), n_rows=n_rows, iters=args.iters,
                   kernels="fallback")


if __name__ == "__main__":
    main()
