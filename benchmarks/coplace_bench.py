"""Nightly co-placement gate (ci/nightly.sh, docs/optimizer.md#placement).

Runs NDS q5 and q72 through the eager plan tier with the placement rule
OFF then ON (SPARK_RAPIDS_TPU_PLACEMENT), cold then warm under fresh
per-fingerprint stats stores, asserting the co-placement contract:

- bit-exact result parity: placement on == off, cold and warm (the rule
  may change WHERE a subtree executes, never what it returns);
- `placement_overlap_ms > 0` on >= 1 plan: the host-placed build side
  measurably overlapped device execution rather than serializing at the
  join (q72's hd/dates dimension subtrees are the expected candidates —
  q5's date dimension is DAG-shared across channels, so the rule must
  decline it and q5 doubles as placement-declines-shared coverage);
- warm placed wall <= warm device-only wall on every plan that placed,
  ON A REAL DEVICE BACKEND (ci/device_smoke.sh): there the host threads
  are genuinely different silicon from the device walk, so an overlap
  that loses wall-clock is a placement-rule regression. Under the CPU
  nightly (JAX_PLATFORMS=cpu) the "device" walk and the host threads
  share the same cores — co-placement cannot win wall-clock by
  construction, so the strict gate would only measure thread-spawn
  overhead; instead the warm-on/warm-off ratio is REPORTED to JSONL
  (the trajectory finally records a co-placement number) and bounded
  loosely (<= 1.5) to catch serialization-class regressions where the
  placed subtree stops overlapping and runs strictly after the walk.

Every row stamps `placement`/`placement_overlap_ms` alongside `backend`
and `session` (tools/lint_metrics.py missing-placement-stamp: an
overlap number is a host-vs-device comparison by construction).
"""
import contextlib
import os
import sys

sys.path.insert(0, ".")

from benchmarks.common import emit_record, parse_args        # noqa: E402
from benchmarks.nds_plans import kernels_of                  # noqa: E402
from benchmarks.nds_plans import (q5_inputs, q5_plan,        # noqa: E402
                                  q72_inputs, q72_plan)


@contextlib.contextmanager
def _placement(on: bool):
    """SPARK_RAPIDS_TPU_PLACEMENT toggle, restored on exit — config
    reads the env at use time, so toggling between runs is the same
    contract the serving layer relies on."""
    key = "SPARK_RAPIDS_TPU_PLACEMENT"
    prev = os.environ.get(key)
    os.environ[key] = "on" if on else "off"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev


def _placed_ops(res):
    """Labels the executed plan ran on the host thread (stamped by
    plan/executor.py's co-placement dispatch)."""
    return sorted(label for label, m in res.metrics.items()
                  if m.placement == "host")


def _overlap_ms(res):
    """Total measured host/device overlap across consuming operators."""
    return sum(m.placement_overlap_ms for m in res.metrics.values())


def _run(name, plan, inputs, n_rows):
    import jax
    from spark_rapids_tpu.plan import PlanExecutor
    from spark_rapids_tpu.plan import stats as stats_mod

    results, runs = {}, {}

    def one(mode, phase, store):
        with _placement(mode == "on"), stats_mod.scoped_store(store):
            ex = PlanExecutor(mode="eager", optimize=True)
            res = ex.execute(plan, inputs)
            results[(mode, phase)] = res.compact().to_pydict()
            runs[(mode, phase)] = res
            sources = (res.optimizer or {}).get("decision_sources") or {}
            emit_record(
                f"coplace_{name}", {"phase": phase}, res.wall_ms, n_rows,
                impl="plan_eager", optimizer="on",
                rules_fired=(res.optimizer or {}).get("rules_fired"),
                kernels=kernels_of(res),
                backend=jax.default_backend(),
                session="",                 # outside serving
                placement=mode,
                placement_overlap_ms=round(_overlap_ms(res), 3),
                placed_ops=_placed_ops(res),
                placement_decisions={k: v for k, v in sources.items()
                                     if k.endswith("/placement")})
            return res

    # separate stores per variant: the off runs must stay a pure
    # device-only baseline — observed walls from a placed run would
    # turn the "off" warm wall into a warm hybrid (docs/adaptive.md)
    for mode in ("off", "on"):
        # path="": must not inherit SPARK_RAPIDS_TPU_STATS_PATH state
        store = stats_mod.StatsStore(capacity=32, path="")
        one(mode, "cold", store)
        one(mode, "warm", store)

    assert (results[("on", "cold")] == results[("off", "cold")]
            == results[("on", "warm")] == results[("off", "warm")]), \
        f"{name}: placement changed the result"

    warm_on, warm_off = runs[("on", "warm")], runs[("off", "warm")]
    placed = _placed_ops(warm_on)
    if placed:
        import jax
        if jax.default_backend() != "cpu":
            # real device: host threads are different silicon — losing
            # wall-clock against the single-backend walk is a regression
            assert warm_on.wall_ms <= warm_off.wall_ms, \
                (f"{name}: warm placed wall {warm_on.wall_ms:.1f} ms "
                 f"exceeded warm device-only wall {warm_off.wall_ms:.1f} "
                 f"ms (placed={placed})")
        else:
            # CPU backend: host threads share the walk's own cores, so
            # only bound the overhead — a placed subtree that stops
            # overlapping (runs strictly after the walk) blows past this
            assert warm_on.wall_ms <= 1.5 * warm_off.wall_ms, \
                (f"{name}: warm placed wall {warm_on.wall_ms:.1f} ms is "
                 f">1.5x the warm device-only wall {warm_off.wall_ms:.1f}"
                 f" ms — the host subtree serialized (placed={placed})")
    # report-not-gate: the on/off warm wall ratio trajectory
    emit_record(f"coplace_{name}", {"phase": "ratio"},
                warm_on.wall_ms, n_rows,
                impl="plan_eager", optimizer="on",
                kernels=kernels_of(warm_on),
                backend=jax.default_backend(), session="",
                placement="on",
                placement_overlap_ms=round(_overlap_ms(warm_on), 3),
                placed_ops=placed,
                warm_wall_ratio=round(
                    warm_on.wall_ms / max(warm_off.wall_ms, 1e-9), 4))
    return warm_on


def main(argv=None):
    args = parse_args(argv)
    n = max(int(100_000 * args.scale), 10_000)

    from benchmarks.bench_nds_q5 import build_tables as bt5
    from benchmarks.bench_nds_q72 import build_tables as bt72

    # q5: the date dimension is DAG-shared across all three channel
    # semi-joins, so _host_placeable must DECLINE every candidate —
    # this query gates "shared subtrees never place" (zero placed ops,
    # results identical by construction of the decline).
    q5_in = q5_inputs(*bt5(n, seed=3))
    w5 = _run("q5", q5_plan(), q5_in,
              n_rows=sum(t.num_rows for t in q5_in.values()))
    assert not _placed_ops(w5), \
        f"q5: shared date dimension was placed ({_placed_ops(w5)})"

    # q72: the hd and dates build sides are exclusive scan+filter
    # subtrees whose certified output bounds fit the cold threshold —
    # the overlap gate lives here.
    q72_in = q72_inputs(*bt72(n, seed=5))
    w72 = _run("q72", q72_plan(), q72_in,
               n_rows=sum(t.num_rows for t in q72_in.values()))
    assert _placed_ops(w72), \
        (f"q72: no subtree placed (decisions="
         f"{(w72.optimizer or {}).get('decision_sources')})")
    assert _overlap_ms(w72) > 0, \
        (f"q72: placed {_placed_ops(w72)} but measured zero overlap — "
         "the host subtree serialized at the join")
    print("co-placement OK", file=sys.stderr)


if __name__ == "__main__":
    main()
