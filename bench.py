"""Headline benchmark: Spark-exact row hashing throughput on device.

Hashing (murmur3_32 + xxhash64 over a 2×int64-column table) is the kernel a
Spark plan leans on hardest — every hash partition, hash join and hash
aggregate runs it over the full batch. The reference measures its kernels with
nvbench locally and publishes nothing (SURVEY.md §6), so the baseline here is
the same XLA program on the host CPU: `vs_baseline` = device rows/s ÷ host
rows/s.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import numpy as np


def _bench(fn, args, iters=20):
    import jax
    out = fn(*args)           # warmup/compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu import dtypes, Column
    from spark_rapids_tpu.columnar import Table
    from spark_rapids_tpu.ops import murmur_hash3_32, xxhash64

    n = 10_000_000
    rng = np.random.default_rng(0)
    keys_np = rng.integers(-(2**62), 2**62, size=n, dtype=np.int64)
    vals_np = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64)

    def step(keys, vals):
        t = Table([Column(dtype=dtypes.INT64, length=n, data=keys),
                   Column(dtype=dtypes.INT64, length=n, data=vals)])
        h32 = murmur_hash3_32(t, seed=42)
        h64 = xxhash64(t)
        return h32.data, h64.data

    jit_step = jax.jit(step)

    dev = jax.devices()[0]
    d_args = (jax.device_put(jnp.asarray(keys_np), dev),
              jax.device_put(jnp.asarray(vals_np), dev))
    dev_s = _bench(jit_step, d_args)
    dev_rows_per_s = n / dev_s

    try:
        cpu = jax.devices("cpu")[0]
        c_args = (jax.device_put(jnp.asarray(keys_np), cpu),
                  jax.device_put(jnp.asarray(vals_np), cpu))
        cpu_s = _bench(jit_step, c_args, iters=3)
        vs_baseline = dev_rows_per_s / (n / cpu_s)
    except Exception:
        vs_baseline = None  # baseline did not run; distinct from measured 1.0

    print(json.dumps({
        "metric": "spark_row_hash_throughput",
        "value": round(dev_rows_per_s / 1e6, 3),
        "unit": "Mrows/s (murmur3_32+xxhash64, 2xint64, 10M rows)",
        "vs_baseline": None if vs_baseline is None else round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
