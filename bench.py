"""Headline benchmark: Spark-exact row hashing throughput on device.

Hashing (murmur3_32 + xxhash64 over a 2×int64-column table) is the kernel a
Spark plan leans on hardest — every hash partition, hash join and hash
aggregate runs it over the full batch. The reference measures its kernels with
nvbench locally and publishes nothing (SURVEY.md §6), so the baseline here is
the same XLA program on the host CPU: `vs_baseline` = device rows/s ÷ host
rows/s.

Hardened (round-2 mandate): on this image the TPU backend can HANG at init,
not just error (round-1 BENCH rc=1; an in-process retry never regains
control from a hung `jax.devices()`). So the measurement runs in a child
process the parent can time out: bounded attempts on the device backend,
then an explicit CPU-fallback measurement with an `error` record. Exactly
ONE JSON line is printed on every path and the exit code is always 0, so the
driver records a parseable result even on a dead tunnel.

Usage: `python bench.py` (orchestrator) — or `python bench.py --measure
[--cpu]` to run one measurement in-process.
"""
import json
import os
import subprocess
import sys
import time
import traceback

N_ROWS = 10_000_000
UNIT = "Mrows/s (murmur3_32+xxhash64, 2xint64, 10M rows)"
DEVICE_ATTEMPTS = 2
DEVICE_TIMEOUT_S = 300
RETRY_SLEEP_S = 15
TUNNEL_PORTS = (8090, 8091, 8092, 8093, 8094)


def probe_tunnel(timeout_s: float = 3.0):
    """Healthz probe for the axon TPU tunnel (same probe as ci/tpu-smoke.sh).

    Returns a human-readable status string; 'dead' means no port answered.
    A dead tunnel makes every TPU op HANG (round-3 BENCH burned a 300 s
    timeout on it), so the orchestrator checks this first and goes straight
    to the CPU fallback in <5 s, recording the probe result so the driver
    can distinguish 'tunnel down' from 'kernel regressed'.
    """
    import urllib.request
    for port in TUNNEL_PORTS:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=timeout_s)
            return f"ok:{port}"
        except Exception:
            continue
    return "dead"


def _bench(fn, args, iters, platform):
    """Steady-state seconds/iter on a device of the given platform; the
    barrier + differencing methodology lives in benchmarks.common (the
    tunnel's block_until_ready is not a reliable barrier — see
    `benchmarks.common.sync`/`steady_state_ms`)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.common import steady_state_ms, sync
    out = fn(*args)           # warmup/compile
    sync(out)
    return steady_state_ms(fn, args, iters, platform) / 1e3


def measure(force_cpu: bool) -> None:
    """Run the measurement in-process and print the ONE JSON line."""
    import jax
    if force_cpu:
        # env-var pinning is unreliable under the axon sitecustomize (it
        # imports jax at interpreter startup); jax.config works unless
        # backends already initialized — then jax.devices("cpu") below still
        # selects the CPU explicitly
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    import numpy as np
    import jax.numpy as jnp
    from spark_rapids_tpu import dtypes, Column
    from spark_rapids_tpu.columnar import Table
    from spark_rapids_tpu.ops import murmur_hash3_32, xxhash64

    n = N_ROWS
    rng = np.random.default_rng(0)
    keys_np = rng.integers(-(2**62), 2**62, size=n, dtype=np.int64)
    vals_np = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64)

    def step(keys, vals):
        t = Table([Column(dtype=dtypes.INT64, length=n, data=keys),
                   Column(dtype=dtypes.INT64, length=n, data=vals)])
        h32 = murmur_hash3_32(t, seed=42)
        h64 = xxhash64(t)
        return h32.data, h64.data

    jit_step = jax.jit(step)

    dev = jax.devices("cpu")[0] if force_cpu else jax.devices()[0]
    d_args = (jax.device_put(jnp.asarray(keys_np), dev),
              jax.device_put(jnp.asarray(vals_np), dev))
    dev_s = _bench(jit_step, d_args, iters=20 if dev.platform != "cpu" else 5,
                   platform=dev.platform)
    dev_rows_per_s = n / dev_s

    vs_baseline = None
    if dev.platform != "cpu":
        try:
            cpu = jax.devices("cpu")[0]
            c_args = (jax.device_put(jnp.asarray(keys_np), cpu),
                      jax.device_put(jnp.asarray(vals_np), cpu))
            cpu_s = _bench(jit_step, c_args, iters=3, platform="cpu")
            vs_baseline = round(dev_rows_per_s / (n / cpu_s), 3)
        except Exception:
            vs_baseline = None  # baseline did not run; distinct from 1.0

    # kernel-registry stamp (docs/kernels.md): this bench times the jnp
    # fused-XLA row hash — the universal lowering, registry-free on every
    # backend — so the honest per-run stamp is "fallback" everywhere,
    # stated explicitly on the CPU-fallback path and the device path alike
    # (stamping the registry's would-be summary here would attribute
    # kernels this run never dispatched)
    kernels = "fallback"

    print(json.dumps({
        "metric": "spark_row_hash_throughput",
        "value": round(dev_rows_per_s / 1e6, 3),
        "unit": UNIT,
        "vs_baseline": vs_baseline,
        "backend": dev.platform,
        "kernels": kernels,
    }))


def _parse_result_line(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
                if rec.get("metric"):
                    return rec
            except json.JSONDecodeError:
                continue
    return None


def orchestrate() -> None:
    """Try the device backend in a killable child; fall back to CPU."""
    errors = []
    health = probe_tunnel()
    if health == "dead" and os.environ.get("SRT_BENCH_FORCE_DEVICE", "") != "1":
        errors.append("tunnel healthz dead on ports "
                      f"{'-'.join(str(p) for p in (TUNNEL_PORTS[0], TUNNEL_PORTS[-1]))}"
                      " — skipping device attempts (set SRT_BENCH_FORCE_DEVICE=1"
                      " to override)")
        print(f"bench: {errors[-1]}", file=sys.stderr)
        _cpu_fallback(errors, health)
        return
    for attempt in range(1, DEVICE_ATTEMPTS + 1):
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--measure"],
                capture_output=True, text=True, timeout=DEVICE_TIMEOUT_S)
            rec = _parse_result_line(p.stdout)
            if p.returncode == 0 and rec is not None and rec.get("value") is not None:
                rec["tunnel_healthz"] = health
                print(json.dumps(rec))
                return
            errors.append(f"attempt {attempt}: rc={p.returncode} "
                          f"stderr={p.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: device measurement timed out "
                          f"after {DEVICE_TIMEOUT_S}s (backend hang)")
            print(f"bench: {errors[-1]}", file=sys.stderr)
            break   # a hung backend stays hung; go straight to CPU fallback
        print(f"bench: {errors[-1]}", file=sys.stderr)
        if attempt < DEVICE_ATTEMPTS:
            time.sleep(RETRY_SLEEP_S)
    _cpu_fallback(errors, health)


def _cpu_fallback(errors, health) -> None:
    """CPU-fallback measurement, still in a killable child."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure", "--cpu"],
            capture_output=True, text=True, timeout=DEVICE_TIMEOUT_S)
        rec = _parse_result_line(p.stdout)
        if rec is not None and rec.get("value") is not None:
            rec["error"] = ("device backend unavailable, measured on CPU: "
                            + " | ".join(errors))
            rec["tunnel_healthz"] = health
            print(json.dumps(rec))
            return
        errors.append(f"cpu fallback: rc={p.returncode} "
                      f"stderr={p.stderr.strip()[-400:]}")
    except subprocess.TimeoutExpired:
        errors.append("cpu fallback: timed out")

    print(json.dumps({
        "metric": "spark_row_hash_throughput",
        "value": None,
        "unit": UNIT,
        "vs_baseline": None,
        "error": " | ".join(errors),
        "tunnel_healthz": health,
    }))


if __name__ == "__main__":
    if "--measure" in sys.argv:
        # no catch-all here: a failed measurement must exit non-zero so the
        # orchestrator retries / falls back instead of accepting an error
        # record as a result
        measure(force_cpu="--cpu" in sys.argv)
    else:
        try:
            orchestrate()
        except Exception as e:
            traceback.print_exc()
            print(json.dumps({
                "metric": "spark_row_hash_throughput",
                "value": None,
                "unit": UNIT,
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}",
            }))
            sys.exit(0)
