#!/bin/bash
# Real-TPU oracle smoke tier: one config per op family, unpinned backend
# (VERDICT r2 "Next round" #4; the reference runs its gtest/JUnit suites on
# the device it ships for — SURVEY.md §4).
#
# The axon tunnel can be down (every TPU op then hangs): probe healthz first
# and fail fast with a distinct exit code so CI can tell "tunnel dead" from
# "parity bug".
set -u
cd "$(dirname "$0")/.."

up=""
for p in 8090 8091 8092 8093 8094; do
  if curl -s -m 5 "http://127.0.0.1:$p/healthz" >/dev/null 2>&1; then up=$p; break; fi
done
if [ -z "$up" ]; then
  echo "tpu-smoke: axon tunnel unreachable (healthz dead on 8090-8094); skipping" >&2
  exit 75   # EX_TEMPFAIL: infrastructure, not a test failure
fi

SRT_TPU_SMOKE=1 timeout "${SRT_TPU_SMOKE_TIMEOUT:-3600}" \
  python -m pytest tests/ -m tpu_smoke -q -rs "$@"
rc=$?
if [ $rc -eq 124 ]; then
  echo "tpu-smoke: timed out (tunnel hang mid-run?)" >&2
elif [ $rc -eq 5 ]; then
  echo "tpu-smoke: pytest collected 0 tests — marker/rootdir configuration error, not a pass" >&2
  exit 70   # EX_SOFTWARE: the tier itself is broken
fi
exit $rc
