#!/usr/bin/env bash
# Arbiter fuzz tier (reference: ci/fuzz-test.sh runs RmmSparkMonteCarlo
# --taskMaxMiB=2048 --gpuMiB=3072 --skewed --allocMode=ASYNC nightly).
set -euo pipefail
cd "$(dirname "$0")/.."

python tools/monte_carlo.py --iterations 3 --tasks 64 --parallelism 12 \
    --gpu-mib 3072 --task-max-mib 2048 --max-task-allocs 8 \
    --shuffle-threads 4 --skewed --skew-amount 0.4
echo "fuzz OK"
