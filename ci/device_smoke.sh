#!/bin/bash
# One-command live-backend smoke (ROADMAP item 5a): the day a real
# accelerator is reachable, run every gate whose nightly form is
# interpret-mode/simulated-mesh parity — so all the "remaining headroom:
# real-TPU numbers" items in docs/kernels.md, docs/distributed.md, and
# docs/optimizer.md#placement resolve in ONE run:
#
#   1. kernel_bench      — Mosaic lowerings interpret=False; the capped-
#                          tier speedup gate ARMS itself on a tpu backend
#                          (benchmarks/kernel_bench.py SPEEDUP_MIN)
#   2. distributed_parity — NDS q5/q72 SPMD on the real mesh (no
#                          --xla_force_host_platform_device_count: the
#                          bench only injects simulated devices when the
#                          flag is absent AND only the host platform
#                          grows them — a tpu backend keeps its chips)
#   3. exchange_bench    — packing + async dispatch on real ICI, where
#                          wire bytes stop being simulated
#   4. coplace_bench     — the STRICT co-placement gate: on a non-cpu
#                          backend the host threads are different silicon
#                          from the device walk, so warm placed wall <=
#                          warm device-only wall is enforced, not just
#                          the reported ratio (docs/optimizer.md#placement)
#
# Backend selection is left to jax (NO JAX_PLATFORMS=cpu, no --cpu):
# whatever live device the environment exposes is what gets measured.
# Like ci/tpu-smoke.sh, a dead axon tunnel is infrastructure, not a
# failure: probe healthz first and exit 75 (EX_TEMPFAIL) so CI can tell
# "tunnel dead" from "device regression". A backend that initializes to
# cpu anyway (no device plugged) exits 75 for the same reason.
set -u
cd "$(dirname "$0")/.."

up=""
for p in 8090 8091 8092 8093 8094; do
  if curl -s -m 5 "http://127.0.0.1:$p/healthz" >/dev/null 2>&1; then up=$p; break; fi
done
if [ -z "$up" ]; then
  echo "device-smoke: axon tunnel unreachable (healthz dead on 8090-8094); skipping" >&2
  exit 75   # EX_TEMPFAIL: infrastructure, not a test failure
fi

backend=$(timeout 120 python -c "import jax; print(jax.default_backend())" 2>/dev/null)
if [ "${backend:-cpu}" = "cpu" ] || [ -z "${backend:-}" ]; then
  echo "device-smoke: no live accelerator backend (got '${backend:-none}'); skipping" >&2
  exit 75
fi
ndev=$(timeout 120 python -c "import jax; print(len(jax.devices()))")
echo "device-smoke: backend=$backend n_devices=$ndev" >&2

set -e
SCALE="${DEVICE_SMOKE_SCALE:-0.2}"
timeout "${DEVICE_SMOKE_TIMEOUT:-3600}" \
  python benchmarks/kernel_bench.py --scale "$SCALE"
if [ "$ndev" -ge 4 ]; then
  timeout "${DEVICE_SMOKE_TIMEOUT:-3600}" \
    python benchmarks/distributed_parity.py --scale "$SCALE"
  timeout "${DEVICE_SMOKE_TIMEOUT:-3600}" \
    python benchmarks/exchange_bench.py --scale "$SCALE"
else
  # the mesh tiers need >= 4 chips; a 1-chip smoke still proves the
  # kernel + co-placement gates, so report the gap instead of failing
  echo "device-smoke: $ndev device(s) < 4 — skipping distributed_parity/exchange_bench (mesh tiers)" >&2
fi
timeout "${DEVICE_SMOKE_TIMEOUT:-3600}" \
  python benchmarks/coplace_bench.py --scale "$SCALE"
echo "device-smoke OK (backend=$backend n_devices=$ndev)"
