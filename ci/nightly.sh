#!/usr/bin/env bash
# Nightly build (reference: ci/nightly-build.sh adds the sanitizer tier and
# extra arches). Here: full suite, larger bench pass, fuzz tier, and the
# multi-chip dry run.
set -euo pipefail
cd "$(dirname "$0")/.."

# native warning gate: new -Wall/-Wextra diagnostics in load-bearing native
# code fail the nightly before anything else runs
python - <<'PY'
from spark_rapids_tpu.native.build import check_warnings
warns = check_warnings()
if warns:
    print("native warnings detected:\n" + "\n".join(warns))
    raise SystemExit(1)
print("native warning gate: clean")
PY

python -m pytest tests/ -q -m ""    # include the nightly-marked tier
python benchmarks/run_all.py --scale 0.01 --iters 5 --cpu
# chaos soak (docs/robustness.md): NDS plans under a seeded faultinj config
# (mixed nonfatal + one fatal) — asserts result parity with the fault-free
# run, non-zero retry/degraded counts, and breaker recovery via
# reset_device(); emits retries/faults_injected/degraded JSONL fields
JAX_PLATFORMS=cpu python benchmarks/chaos_soak.py --scale 0.2 --cpu
# multi-session serving soak (docs/serving.md): 8 concurrent tenant
# sessions submit a mixed q3/q5 workload through serving.ServingScheduler
# under the same seeded chaos config (transients + one fatal) — asserts
# per-session bit-exact parity for every completion, zero failed/starved
# sessions with a bounded p99 queue wait, >=1 parity-checked result-cache
# hit, and breaker recovery after reset_device(); emits one JSONL row per
# session with the session/queue_wait_ms/cache_hit stamps
# (lint_metrics-enforced)
JAX_PLATFORMS=cpu python benchmarks/chaos_soak.py --scale 0.2 --cpu --sessions 8
# fleet soak (docs/serving.md#fleet): the same chaos storm through
# serving.FleetScheduler — 8 tenant sessions over 3 executor workers with
# one worker KILLED mid-storm while holding in-flight work. Asserts zero
# failed sessions (dead worker's queued jobs replay on survivors),
# bit-exact per-session parity for every completion, a bounded p99 queue
# wait, and >=1 parity-checked cache hit SERVED by a different worker
# than the one that COMPUTED it (consistent-hash locality + promotion);
# per-session JSONL rows carry the worker_id stamp (lint_metrics-enforced).
# The run then adds a SELF-HEALING phase (docs/serving.md#fleet-self-
# healing) on a respawning fleet: a kill, two poison-plan breaker trips
# on distinct workers, and a graceful drain, all mid-storm — asserts the
# fleet heals back to its target size with zero failed sessions, the
# poison fingerprint quarantined after the second distinct-worker trip
# (never a third), a post-kill replica cache hit from a different
# worker, and a gossip-warmed rehome (observed-bytes charge, one
# compile); the self-heal JSONL row stamps respawns + worker_id
# (lint_metrics missing-respawn-stamp rule)
JAX_PLATFORMS=cpu python benchmarks/chaos_soak.py --scale 0.2 --cpu --sessions 8 --workers 3
# lockdep-armed fleet soak (runtime/lockdep.py, docs/analysis.md#
# concurrency-invariants): the same storm — self-healing phase included,
# so the respawn/drain/gossip paths are witnessed too — with every
# engine lock traced by the runtime lock-order witness; FAILS on any
# observed lock-order cycle or any dynamic edge missing from the static
# linter's graph (tools/lint_concurrency.py), and rows stamp
# lockdep_edges/lockdep_cycles so the JSONL history shows witness
# coverage
JAX_PLATFORMS=cpu SPARK_RAPIDS_TPU_LOCKDEP=1 \
    python benchmarks/chaos_soak.py --scale 0.2 --cpu --sessions 8 --workers 3
# optimizer parity (docs/optimizer.md): the four NDS plans, capped tier,
# optimizer off vs on — asserts result parity, nonzero pruned-column
# counts on q5/q72, and a fingerprint-keyed jit-cache hit on a rebuilt
# plan; emits optimizer/rules_fired JSONL fields
JAX_PLATFORMS=cpu python benchmarks/optimizer_parity.py --scale 0.1 --cpu
# adaptive-execution gate (docs/adaptive.md): NDS q5/q72 cold then warm
# under a fresh per-fingerprint stats store — bit-exact parity (warm ==
# cold == adaptivity-off), zero cap-escalation retries on the warm run
# (observed-cap seeding across executor instances), >=1 stats-driven
# build-side rewrite fired warm (through verify_rewrite), and warm wall
# <= cold wall; every JSONL row carries adaptive/stats_hits stamps
JAX_PLATFORMS=cpu python benchmarks/adaptive_bench.py --scale 0.1 --cpu
# co-placement gate (docs/optimizer.md#placement): NDS q5/q72 eager tier,
# placement rule off vs on, cold then warm under fresh stats stores —
# bit-exact parity on == off, q5 declines its DAG-shared date dimension
# (zero placed ops), q72 places its hd/dates build sides with measured
# placement_overlap_ms > 0, and the warm-on/warm-off wall ratio is
# reported to JSONL (gated strictly only on a real device backend, where
# the host threads are different silicon — ci/device_smoke.sh; on this
# CPU runner the ratio is bounded <= 1.5x against serialization
# regressions); rows stamp placement/placement_overlap_ms alongside
# backend+session (lint_metrics missing-placement-stamp rule)
JAX_PLATFORMS=cpu python benchmarks/coplace_bench.py --scale 0.1 --cpu
# streaming-scan gate (docs/io.md): parquet-bound vs table-bound parity in
# both tiers, nonzero row groups pruned on a selective predicate (with
# measurably fewer decoded bytes), and decode/execute overlap > 0 with the
# prefetch pipeline enabled; emits io_* + backend JSONL fields
JAX_PLATFORMS=cpu python benchmarks/streaming_scan.py --scale 0.5 --cpu
# distributed parity (docs/distributed.md): NDS q5/q72 through the
# full-plan SPMD tier on a 4-device simulated mesh — exact parity vs the
# single-device eager tier, >=1 broadcast and >=1 shuffle join selected by
# exchange_planning (checked on the executed plan), one sink gather, and
# nonzero exchange-bytes; emits n_devices/mesh_axis/exchange_bytes JSONL
# fields
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/distributed_parity.py --scale 0.2 --cpu
# exchange transport (docs/distributed.md#transport): NDS q5/q72 on the
# 4-device mesh with packing + async dispatch forced on — exact parity
# packed vs pack-off vs single-device, wire <= logical on every edge with
# wire <= 0.8x logical on at least one, wire <= the certified per-edge
# bound (footprint.check_observed), nonzero exchange/compute overlap-ms,
# and JSONL rows carrying exchange_bytes_wire/_logical/_overlap_ms
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/exchange_bench.py --scale 0.2 --cpu
# kernel-registry gate (docs/kernels.md): per-kernel parity (each Pallas
# kernel FORCED against its XLA fallback — interpret mode on CPU) plus the
# NDS q5/q72 capped tier registry-on vs forced-fallback with exact parity;
# on this CPU runner it additionally asserts auto-selection picked no
# accelerator kernel, and the capped-tier speedup gate arms itself
# whenever a TPU backend is present; emits per-kernel JSONL rows with the
# `kernels` stamp
JAX_PLATFORMS=cpu python benchmarks/kernel_bench.py --scale 0.05 --cpu
# resource-certifier gate (docs/analysis.md): NDS q5/q72 eager, cold and
# warm under a fresh stats store — certified [lo,hi] row bounds hold for
# every operator (bytes too, eager tier), a 1-byte budget rejects at
# admission with the operator named, and the bound-tightness ratio
# (certified/observed, median + max) is emitted to JSONL — reported, not
# gated: bounds are sound by construction, this tracks whether they stay
# USEFUL
JAX_PLATFORMS=cpu python benchmarks/footprint_bench.py --scale 0.1 --cpu
# deep plan fuzz (docs/analysis.md): a seeded sweep of >=200 random plans
# over all 11 operator kinds — static verification (authored + optimized,
# per-rule re-validation), no optimizer fall-backs, small-plan eager
# parity optimized-vs-unoptimized (error parity included), cold-vs-warm
# adaptive parity, and certifier soundness + monotonicity (property 5:
# observed rows/bytes inside certified bounds on every run, optimized
# root bound <= authored); emits one JSONL summary row, and any failing
# seed replays standalone via
# `python -m spark_rapids_tpu.analysis.fuzz --start <seed> --count 1 -v`
JAX_PLATFORMS=cpu python benchmarks/plan_fuzz.py --seed0 1000 --count 200 --cpu
./ci/fuzz-test.sh
./ci/sanitizer.sh
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('multichip OK')"
# Multi-PROCESS mesh proof (jax.distributed, 2 procs x 4 CPU devices) runs
# in the pytest tier above: tests/test_multiproc_mesh.py.
# Real-TPU oracle smoke: exit 75 (tunnel unreachable) is tolerated — the tier
# runs whenever the axon tunnel is up, and a dead tunnel is infrastructure,
# not a nightly failure.
./ci/tpu-smoke.sh || { rc=$?; [ $rc -eq 75 ] || exit $rc; }
echo "nightly OK"
