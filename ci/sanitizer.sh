#!/usr/bin/env bash
# Native-test + sanitizer tier (reference: gtest executables, SURVEY.md §4
# tier 1, and the Compute Sanitizer run, tier 3). Compiles the native test
# driver WITH the library sources under ASan+UBSan and runs it directly —
# every C++ path memcheck'd with no interpreter in the way.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -d)
trap 'rm -rf "$OUT"' EXIT

python - <<EOF
import numpy as np, pyarrow as pa, pyarrow.parquet as pq
n = 1000
t = pa.table({
    "x": pa.array(np.arange(n), pa.int64()),
    "s": pa.array([None if i % 9 == 0 else f"s{i % 50}" for i in range(n)]),
})
pq.write_table(t, "$OUT/smoke.parquet", row_group_size=256,
               compression="SNAPPY")
t2 = pa.table({
    "li": pa.array([[1, 2], None, []] * 100, pa.list_(pa.int64())),
    "st": pa.array([{"a": 1, "b": "x"}, None] * 150,
                   pa.struct([("a", pa.int64()), ("b", pa.string())])),
    "dl": pa.array(list(range(300))),
    # generalized nesting (kind-4 decode paths under ASan)
    "mp": pa.array([[("k", 1)], None, []] * 100,
                   pa.map_(pa.string(), pa.int64())),
    "ls": pa.array([[{"x": 1}], None, []] * 100,
                   pa.list_(pa.struct([("x", pa.int64())]))),
    "sl": pa.array([{"v": [1, 2]}, None] * 150,
                   pa.struct([("v", pa.list_(pa.int64()))])),
})
pq.write_table(t2, "$OUT/nested.parquet", row_group_size=128,
               use_dictionary=False, data_page_version="2.0",
               column_encoding={"li.list.element": "DELTA_BINARY_PACKED",
                                "st.a": "DELTA_BINARY_PACKED",
                                "st.b": "DELTA_BYTE_ARRAY",
                                "dl": "DELTA_BINARY_PACKED"})
EOF

g++ -std=c++17 -O1 -g -pthread -fsanitize=address,undefined \
    -fno-omit-frame-pointer -Wall -Wextra \
    -o "$OUT/native_smoke" \
    spark_rapids_tpu/native/tests/native_smoke.cpp \
    spark_rapids_tpu/native/resource_adaptor.cpp \
    spark_rapids_tpu/native/parquet_reader.cpp \
    spark_rapids_tpu/native/parquet_footer.cpp \
    -lz -lzstd -l:libsnappy.so.1

ASAN_OPTIONS="abort_on_error=1" UBSAN_OPTIONS="halt_on_error=1" \
    "$OUT/native_smoke" "$OUT/smoke.parquet" "$OUT/nested.parquet"
echo "sanitizer OK"
