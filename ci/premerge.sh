#!/usr/bin/env bash
# Premerge gate (reference: ci/premerge-build.sh runs `mvn verify` with tests
# on). Full unit suite on the 8-device CPU mesh + native build + bench smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

python -c "import spark_rapids_tpu; print('import ok:', spark_rapids_tpu.__name__)"
python -m pytest tests/ -x -q
python benchmarks/run_all.py --scale 0.002 --iters 2 --cpu
python tools/monte_carlo.py --tasks 16 --parallelism 4 --gpu-mib 512 \
    --task-max-mib 384 --shuffle-threads 2 --seed 1
echo "premerge OK"
