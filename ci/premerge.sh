#!/usr/bin/env bash
# Premerge gate (reference: ci/premerge-build.sh runs `mvn verify` with tests
# on). Full unit suite on the 8-device CPU mesh + native build + bench smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

python -c "import spark_rapids_tpu; print('import ok:', spark_rapids_tpu.__name__)"
# JAX-hazard linter (tools/lint_hazards.py, docs/analysis.md): AST-checks
# the known hazard patterns (self capture in jit closure caches, host
# sync on traced values, tracer branches, env reads outside config.py,
# nondeterministic iteration feeding fingerprints, inconsistent lock
# guards on shared-state classes, unguarded module-global mutation);
# vetted exceptions live in tools/lint_hazards_allowlist.txt with
# one-line justifications — STALE entries fail the run, prune them
python tools/lint_hazards.py spark_rapids_tpu
# bench-JSONL stamp linter (tools/lint_metrics.py): every emit_record/
# run_config call site stamps `kernels`, every raw JSONL record carries
# backend/n_devices/kernels — the ROADMAP cross-cutting rule, enforced
python tools/lint_metrics.py
# concurrency linter (tools/lint_concurrency.py, docs/analysis.md#
# concurrency-invariants): whole-tree lock-order graph (interprocedural
# "calls F while holding L" edges, any cycle fails with a witness path),
# unbounded blocking calls reached under a lock, and FleetWorker
# isolation (worker-owned state only via the sanctioned surfaces);
# vetted exceptions + witness-proven `edge::` declarations live in
# tools/lint_concurrency_allowlist.txt — STALE entries fail the run
python tools/lint_concurrency.py
# fixed fuzz corpus (analysis/fuzz.py): 24 seeded random plans covering
# all 11 node kinds — verify + optimize (per-rule re-validation) + eager
# optimized-vs-unoptimized parity + cold-vs-warm adaptive parity +
# certifier soundness/monotonicity; the nightly runs the deep sweep
JAX_PLATFORMS=cpu python -m spark_rapids_tpu.analysis.fuzz --start 0 --count 24 --cpu
python -m pytest tests/ -x -q
python benchmarks/run_all.py --scale 0.002 --iters 2 --cpu
python tools/monte_carlo.py --tasks 16 --parallelism 4 --gpu-mib 512 \
    --task-max-mib 384 --shuffle-threads 2 --seed 1
echo "premerge OK"
