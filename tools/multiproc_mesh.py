"""Multi-PROCESS mesh proof: the distributed tier over jax.distributed.

Everything in parallel/ runs as SPMD programs over a Mesh; the v5p-64 north
star (SURVEY.md §2.4) is a MULTI-HOST mesh, where the same programs execute
with each host driving only its local chips and XLA collectives riding
ICI/DCN between them. This tool proves that path end to end on CPU: it
spawns N worker processes, each `jax.distributed.initialize`d with
--xla_force_host_platform_device_count local CPU devices, builds the GLOBAL
8-device mesh, feeds process-local shards via
jax.make_array_from_process_local_data, and runs the distributed relational
tier (groupby → ICI all-to-all → final agg; hash-exchange inner join; the
typed-key semi join) exactly as the single-process dryrun does — same code,
multi-process runtime (the reference's analogue: its NCCL/UCX shuffle runs
one rank per executor process).

Usage:
    python tools/multiproc_mesh.py                 # orchestrate 2x4 procs
    python tools/multiproc_mesh.py --worker PID    # internal
Exit 0 and one "MULTIPROC MESH OK" line per worker on success.
"""
import argparse
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# geometry is env-parametrized so CI can prove N>2 processes too
# (default 2x4; the v5p north star is 16 hosts x 4 chips)
N_PROCS = int(os.environ.get("SRT_MULTIPROC_PROCS", "2"))
LOCAL_DEVICES = int(os.environ.get("SRT_MULTIPROC_LOCAL_DEVICES", "4"))


def worker(pid: int, port: int) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=N_PROCS,
                               process_id=pid)
    assert len(jax.local_devices()) == LOCAL_DEVICES, jax.local_devices()
    n_dev = N_PROCS * LOCAL_DEVICES
    assert jax.device_count() == n_dev, jax.device_count()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, REPO)
    from spark_rapids_tpu.parallel import (distributed_groupby,
                                           distributed_inner_join,
                                           distributed_left_semi_join_keyed,
                                           encode_key_columns)
    from spark_rapids_tpu import Column, dtypes

    mesh = Mesh(np.array(jax.devices()).reshape(n_dev), ("data",))
    sh = NamedSharding(mesh, P("data"))
    n = 16 * n_dev                       # global rows

    def dist(host_global):
        """Global array from this process's slice of host data (each
        process feeds only its own rows — the multi-host ingestion path)."""
        m = len(host_global)
        chunk = m // N_PROCS
        lo = pid * chunk
        return jax.make_array_from_process_local_data(
            sh, np.asarray(host_global[lo:lo + chunk]), (m,))

    keys_h = (np.arange(n) % 7).astype(np.int64)
    vals_h = np.arange(n, dtype=np.int64)
    keys, vals = dist(keys_h), dist(vals_h)

    # distributed groupby: partial agg -> all-to-all by key hash -> final
    gk, (gsum, gcnt), gvalid, overflow = distributed_groupby(
        mesh, keys, vals, ["sum", "count"], key_cap=16)
    groups, total, ssum, ovf = jax.jit(
        lambda v, c, s, o: (jnp.sum(v.astype(jnp.int32)),
                            jnp.sum(jnp.where(v, c, 0)),
                            jnp.sum(jnp.where(v, s, 0)),
                            jnp.any(o)))(gvalid, gcnt, gsum, overflow)
    assert not bool(ovf)
    assert int(groups) == 7 and int(total) == n, (int(groups), int(total))
    assert int(ssum) == int(vals_h.sum())

    # distributed inner join (hash exchange both sides)
    rk = dist(np.arange(0, n, 2, dtype=np.int64) % 7)
    rv = dist(np.arange(n // 2, dtype=np.int64))
    _, _, _, ivalid, iover = distributed_inner_join(
        mesh, keys, vals, rk, rv, row_cap=2 * n * n // 7,
        slack=float(n_dev))
    jrows, jovf = jax.jit(lambda v, o: (jnp.sum(v.astype(jnp.int64)),
                                        jnp.any(o)))(ivalid, iover)
    assert not bool(jovf)
    # every left row matches n/2/7-ish right rows; exact count from numpy
    import collections
    rcnt = collections.Counter((np.arange(0, n, 2) % 7).tolist())
    want = sum(rcnt[int(k)] for k in keys_h)
    assert int(jrows) == want, (int(jrows), want)

    # typed tier: string keys through the word codec + Spark-exact hash
    vocab = ["apple", "banana", "", "cherry"]
    scol = Column.from_pylist([vocab[i % 4] for i in range(n)], dtypes.STRING)
    words, specs = encode_key_columns([scol], max_bytes=[8])
    l_words = [dist(np.asarray(w)) for w in words]
    r_words = [dist(np.asarray(w[::2])) for w in words]   # evens: all vocab
    lv = dist(np.arange(n, dtype=np.int64))
    _, _, svalid, sover = distributed_left_semi_join_keyed(
        mesh, l_words, [lv], r_words, specs, slack=float(n_dev))
    srows, sovf = jax.jit(lambda v, o: (jnp.sum(v.astype(jnp.int64)),
                                        jnp.any(o)))(svalid, sover)
    assert not bool(sovf)
    # right side holds the even-indexed rows, i.e. vocab[0] and vocab[2]
    # only -> exactly the even-vocab half of the left side matches
    assert int(srows) == n // 2, int(srows)

    print(f"MULTIPROC MESH OK proc={pid}/{N_PROCS} devices={n_dev} "
          f"groups={int(groups)} join_rows={int(jrows)} semi={int(srows)}",
          flush=True)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_once(timeout_s: float) -> int:
    """Spawn the workers, wait with a shared deadline, ALWAYS reap them
    (a worker stuck in a distributed barrier must not outlive its failed
    peer, hold the inherited stdout pipe open, or pin the CPU devices)."""
    import time
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count="
                        f"{LOCAL_DEVICES}").strip()
    port = _free_port()
    procs = []
    rc = 0
    try:
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", str(i),
             "--port", str(port)], env=env, cwd=REPO)
            for i in range(N_PROCS)]
        deadline = time.monotonic() + timeout_s
        for i, p in enumerate(procs):
            try:
                p.wait(timeout=max(1.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                print(f"worker {i} TIMED OUT after {timeout_s:.0f}s",
                      file=sys.stderr)
                rc = 1
                break
            if p.returncode != 0:
                print(f"worker {i} FAILED rc={p.returncode}",
                      file=sys.stderr)
                rc = 1
                break                     # kill the peer in finally: it is
                #                           blocked on a collective barrier
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=480.0,
                    help="per-attempt deadline for all workers")
    args = ap.parse_args(argv)
    if args.worker is not None:
        worker(args.worker, args.port)
        return 0
    rc = _run_once(args.timeout)
    if rc != 0:
        # one retry on a fresh port: _free_port is inherently TOCTOU (the
        # port is released before the coordinator binds it) and a busy CI
        # host can steal it in the window
        print("retrying once on a fresh port", file=sys.stderr)
        rc = _run_once(args.timeout)
    return rc


if __name__ == "__main__":
    sys.exit(main())
