"""A/B measurement of the round-4 scatter-free relational redesign.

Round 4 rewrote ops/aggregate.py + ops/join.py around measured primitive
costs but shipped no number (VERDICT r4 Missing #1). This tool produces the
number: it checks out the pre-redesign tree (round-3 final, the last commit
with the searchsorted/scatter design) into a git worktree and runs the SAME
bench harness (benchmarks/bench_groupby.py + bench_join.py, byte-identical
between the two revisions — verified with `git diff 123f6ad HEAD`) against
both implementations, on the same backend, in fresh subprocesses.

BASELINE.json shapes: configs[1] groupby sum/count, single int32 key, 10M
rows (also the 100-key variant); configs[2] inner join 10M x 1M int64 keys.

Usage:
    python tools/ab_relational.py [--scale 1.0] [--iters 5] [--device]
                                  [--old-rev 123f6ad]
Appends one record per (impl, bench, axes) to tools/ab_relational.jsonl and
prints a speedup summary. Default backend is CPU (`--cpu` benches — no
tunnel needed); --device drops the pin for the real-chip capture.
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OLD_WT = os.path.join(REPO, ".ab_old")
BENCHES = ("benchmarks/bench_groupby.py", "benchmarks/bench_join.py")


def ensure_worktree(rev: str) -> str:
    if not os.path.isdir(OLD_WT):
        subprocess.run(["git", "worktree", "add", "--detach", OLD_WT, rev],
                       cwd=REPO, check=True, capture_output=True)
    head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                          cwd=OLD_WT, check=True, capture_output=True,
                          text=True).stdout.strip()
    return head


def run_tree(root: str, impl: str, rev: str, args) -> list:
    recs = []
    env = dict(os.environ)
    for bench in BENCHES:
        cmd = [sys.executable, bench, "--scale", str(args.scale),
               "--iters", str(args.iters)]
        if not args.device:
            cmd.append("--cpu")
        r = subprocess.run(cmd, cwd=root, env=env, capture_output=True,
                           text=True, timeout=3600)
        if r.returncode != 0:
            print(f"FAIL {impl} {bench}: {r.stderr[-500:]}", file=sys.stderr)
            continue
        for line in r.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                rec = json.loads(line)
                rec.update({"impl": impl, "rev": rev,
                            "backend": "device" if args.device else "cpu"})
                recs.append(rec)
                print(json.dumps(rec), flush=True)
    return recs


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--device", action="store_true",
                    help="measure on the default (TPU) backend instead of CPU")
    ap.add_argument("--old-rev", default="123f6ad",
                    help="pre-redesign revision (round-3 final)")
    ap.add_argument("--out", default=os.path.join(REPO, "tools",
                                                  "ab_relational.jsonl"))
    args = ap.parse_args(argv)

    old_rev = ensure_worktree(args.old_rev)
    new_rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=REPO, check=True, capture_output=True,
                             text=True).stdout.strip()
    recs = run_tree(OLD_WT, "old", old_rev, args)
    recs += run_tree(REPO, "new", new_rev, args)

    with open(args.out, "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")

    # speedup summary: match (bench, axes) pairs across impls
    def key(r):
        return (r["bench"], json.dumps(r["axes"], sort_keys=True))
    old = {key(r): r for r in recs if r["impl"] == "old"}
    new = {key(r): r for r in recs if r["impl"] == "new"}
    for k in sorted(old.keys() & new.keys()):
        sp = old[k]["ms"] / new[k]["ms"]
        print(f"SPEEDUP {k[0]} {k[1]}: old {old[k]['ms']:.1f} ms -> "
              f"new {new[k]['ms']:.1f} ms  ({sp:.2f}x)")


if __name__ == "__main__":
    main()
