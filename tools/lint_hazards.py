#!/usr/bin/env python
"""AST-based JAX-hazard linter for the engine codebase (docs/analysis.md).

Every rule here is a bug class a past review actually caught by hand —
this makes the catch permanent and premerge-enforced (ci/premerge.sh):

- ``jit-self-capture``: `self` referenced inside a function traced by
  `jax.jit`/`pjit`/`shard_map`. A jitted callable closes over everything
  it references; cached process-globally (the distributed tier's
  jitted-primitive cache) a bound `self` pins the executor — and its
  plan/LRU graph — long after the session died (the PR 5 review finding).
- ``host-sync-in-jit``: `np.asarray`/`np.array`/`jax.device_get`/
  `.item()`/`float()`/`int()`/`bool()` on values inside a traced
  function — a host round-trip per call on the hot path (or a trace
  error). Shape/static lookups (`x.shape[0]`, `len(...)`) are exempt.
- ``tracer-branch``: Python `if`/`while` on an expression derived from a
  traced function's parameters — tracers have no truth value; the branch
  either crashes or silently bakes in one trace-time path.
- ``env-outside-config``: `os.environ`/`os.getenv` anywhere but
  `config.py`. Knobs are read-at-use through config.py so tests can
  monkeypatch and the optimizer can key its caches on them
  (the SPARK_RAPIDS_TPU_BROADCAST_ROWS cache-key fix came from review).
- ``fingerprint-iteration``: unsorted `.items()`/`.keys()`/`.values()`
  or `set()`/`frozenset()` iteration inside fingerprint-computing
  functions — nondeterministic order feeding a structural hash silently
  splits the compiled-program cache (or worse, collides).
- ``lock-discipline``: inconsistent lock guards in a lock-owning class
  (one that assigns ``threading.Lock()``/``RLock()``/``Condition()``
  to an attribute — a ``Condition(self._lock)`` is the same sync
  object as the lock it wraps, so ``with self._cv:`` regions count as
  locked whatever the condition is named).
  Any attribute the class mutates under its lock somewhere is SHARED
  STATE; mutating it anywhere else without the lock is a race waiting
  for a second thread (the PR 11 thread-safety classes — `StatsStore`,
  `KernelRegistry` — are now machine-checked). ``__init__``/
  ``__post_init__`` and ``*_locked`` helper methods (the
  called-under-lock convention) are exempt call sites.
- ``global-mutation``: a module global reassigned (``global X; X = ...``)
  outside any lock-shaped ``with`` block. Racing first-use
  initializers construct twice — for stateful singletons (the stats
  store's persistence replay, faultinj's saved-original tables) that is
  double-counted state, not just wasted work. Idempotent pure-value
  caches belong in the allowlist with that justification.

Vetted exceptions live in the allowlist (default
``tools/lint_hazards_allowlist.txt``), one per line::

    <repo/relative/path.py>::<rule>::<qualified.context>  # justification

The justification is REQUIRED — an allowlist entry without a reason
fails the run. A STALE entry (one matching no current finding) is a
FAILURE too, not a note: an entry that outlives its finding is a
standing suppression of whatever regresses into that slot next — prune
it in the same change that fixed the code. Usage::

    python tools/lint_hazards.py [paths...] [--allowlist FILE] [--list]

Exit status 1 when any unsuppressed finding remains, or any allowlist
entry has gone stale.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

_JIT_NAMES = {"jit", "pjit", "shard_map"}
_HOST_SYNC_DOTTED = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array", "jax.device_get", "onp.asarray"}
_CASTS = {"float", "int", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "names", "num_rows",
                 "itemsize", "nbytes"}
_STATIC_CALLS = {"isinstance", "len", "getattr", "hasattr", "callable",
                 "type", "range", "enumerate", "zip"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    context: str         # dotted qualname of the enclosing def/class
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.context)

    def __str__(self):
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.context or '<module>'}: {self.message}")


def _scope_walk(stmt):
    """ast.walk that stays in the current lexical scope: descends into
    everything EXCEPT nested def/class bodies (each is linted as its own
    scope — descending would double-report their findings under every
    enclosing qualname). Lambdas count as same-scope: they cannot contain
    statements, and jit-wrapped lambdas nested in builder lambdas are
    this scope's business."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


def _dotted(node) -> str:
    """'jax.jit' for Attribute/Name chains; '' for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_callable(func) -> bool:
    d = _dotted(func)
    return bool(d) and d.split(".")[-1] in _JIT_NAMES


def _is_jit_decorator(dec) -> bool:
    if _is_jit_callable(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_callable(dec.func):
            return True
        if _dotted(dec.func).split(".")[-1] == "partial":
            return any(_is_jit_callable(a) for a in dec.args)
    return False


def _func_params(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _static_params(jit_call: Optional[ast.Call], fn) -> Set[str]:
    """Parameter names `static_argnames`/`static_argnums` pin at trace
    time — python control flow on THOSE is legitimate specialization,
    not a tracer branch."""
    if jit_call is None:
        return set()
    out: Set[str] = set()
    a = getattr(fn, "args", None)
    positional = ([p.arg for p in a.posonlyargs + a.args]
                  if a is not None else [])
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            out.update(v.value for v in vals
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, str))
        elif kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int) and \
                        v.value < len(positional):
                    out.add(positional[v.value])
    return out


def _refs_param_value(node, params: Set[str]) -> bool:
    """Whether the expression branches on a parameter's VALUE (a tracer),
    as opposed to static metadata (shapes, dtypes, isinstance, is None)."""
    if isinstance(node, ast.Name):
        return node.id in params
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _refs_param_value(node.value, params)
    if isinstance(node, ast.Subscript):
        return _refs_param_value(node.value, params)
    if isinstance(node, ast.Call):
        if _dotted(node.func).split(".")[-1] in _STATIC_CALLS:
            return False
        return any(_refs_param_value(a, params)
                   for a in list(node.args) + [k.value
                                               for k in node.keywords])
    if isinstance(node, ast.Compare):
        # `x is None` / `x is not None` is a host-side identity check
        if all(isinstance(c, (ast.Constant,)) and c.value is None
               for c in node.comparators) and \
                all(isinstance(o, (ast.Is, ast.IsNot)) for o in node.ops):
            return False
    return any(_refs_param_value(c, params)
               for c in ast.iter_child_nodes(node))


class _ModuleLinter:
    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.findings: List[Finding] = []
        self.is_config = os.path.basename(path) == "config.py"

    # ---- entry ------------------------------------------------------------
    def run(self) -> List[Finding]:
        self._scan_scope(self.tree.body, [])
        self._scan_env(self.tree)
        self._scan_locking(self.tree.body, [])
        self._scan_globals(self.tree.body, [])
        return self.findings

    def _add(self, rule: str, node, qual: List[str], msg: str):
        self.findings.append(Finding(rule, self.rel,
                                     getattr(node, "lineno", 0),
                                     ".".join(qual), msg))

    # ---- traced-function discovery ----------------------------------------
    def _scan_scope(self, body, qual: List[str]):
        """One lexical scope: find functions traced by jit/shard_map (as
        direct lambda/def arguments, decorated defs, or local defs passed
        by name) and lint their bodies; recurse into nested scopes."""
        local_defs: Dict[str, ast.AST] = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[stmt.name] = stmt
        traced: List[Tuple[ast.AST, List[str], Optional[ast.Call]]] = []
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue          # its own scope; recursed below
            for node in _scope_walk(stmt):
                if isinstance(node, ast.Call) and \
                        _is_jit_callable(node.func):
                    for arg in node.args[:1]:
                        if isinstance(arg, ast.Lambda):
                            traced.append((arg, qual + ["<lambda>"], node))
                        elif isinstance(arg, ast.Name) and \
                                arg.id in local_defs:
                            fn = local_defs[arg.id]
                            traced.append((fn, qual + [fn.name], node))
                        elif any(isinstance(n, ast.Name) and
                                 n.id == "self"
                                 for n in ast.walk(arg)):
                            # jax.jit(self._prim) / jax.jit(partial(
                            # self._prim, ...)): jitting a bound method
                            # IS the capture — no lambda body to lint,
                            # the callable itself pins the instance
                            self._add(
                                "jit-self-capture", arg, qual,
                                "bound method (or partial over `self`) "
                                "passed to jit/shard_map — the compiled "
                                "callable pins the instance for the "
                                "cache's lifetime; hoist the needed "
                                "state into locals and trace a free "
                                "function")
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in stmt.decorator_list:
                    if _is_jit_decorator(d):
                        traced.append((stmt, qual + [stmt.name],
                                       d if isinstance(d, ast.Call)
                                       else None))
                        break
        seen = set()
        for fn, fq, jit_call in traced:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            self._lint_traced(fn, fq, _static_params(jit_call, fn))
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_scope(stmt.body, qual + [stmt.name])
            elif isinstance(stmt, ast.ClassDef):
                self._scan_scope(stmt.body, qual + [stmt.name])

    # ---- rules over one traced function ------------------------------------
    def _lint_traced(self, fn, qual: List[str],
                     static: Optional[Set[str]] = None):
        params = _func_params(fn) - (static or set())
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in [n for stmt in body for n in ast.walk(stmt)]:
            if isinstance(node, ast.Name) and node.id == "self" \
                    and "self" not in params:
                self._add("jit-self-capture", node, qual,
                          "`self` captured inside a jit/shard_map-traced "
                          "function — the compiled callable pins the "
                          "instance (and everything it references) for "
                          "the cache's lifetime; close over locals "
                          "instead")
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in _HOST_SYNC_DOTTED:
                    self._add("host-sync-in-jit", node, qual,
                              f"{d}() on a traced value forces a "
                              "device->host sync (or a trace error) "
                              "inside the compiled hot path")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    self._add("host-sync-in-jit", node, qual,
                              ".item() on a traced value forces a "
                              "device->host sync inside the compiled "
                              "hot path")
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in _CASTS and len(node.args) == 1 \
                        and _refs_param_value(node.args[0], params):
                    self._add("host-sync-in-jit", node, qual,
                              f"{node.func.id}() of a traced value "
                              "forces a device->host sync; compute with "
                              "jnp and keep it on device")
            elif isinstance(node, (ast.If, ast.While)):
                if _refs_param_value(node.test, params):
                    self._add("tracer-branch", node, qual,
                              "python control flow on a traced "
                              "expression — tracers have no truth "
                              "value; use jnp.where/lax.cond or hoist "
                              "the decision out of the trace")

    # ---- module-wide rules -------------------------------------------------
    def _scan_env(self, tree: ast.Module):
        fingerprints: List[Tuple[ast.AST, List[str]]] = []

        def walk(body, qual):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    fq = qual + [stmt.name]
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and (
                            "fingerprint" in stmt.name
                            or stmt.name.startswith("_fp")):
                        fingerprints.append((stmt, fq))
                    walk(stmt.body, fq)
                    continue
                if not self.is_config:
                    for node in ast.walk(stmt):
                        # `from os import environ/getenv` aliases the
                        # read past the dotted-form check below — flag
                        # the import itself
                        if isinstance(node, ast.ImportFrom) and \
                                node.module == "os":
                            for alias in node.names:
                                if alias.name in ("environ", "getenv"):
                                    self._add(
                                        "env-outside-config", node, qual,
                                        f"from os import {alias.name} "
                                        "outside config.py breaks the "
                                        "read-at-use knob contract "
                                        "(tests monkeypatch config.py; "
                                        "caches key on its getters)")
                            continue
                        # match the `os.environ`/`os.getenv` Attribute
                        # itself (never the wrapping Call/Subscript —
                        # matching both would report every use twice)
                        if not isinstance(node, ast.Attribute):
                            continue
                        d = _dotted(node)
                        if d in ("os.environ", "os.getenv"):
                            self._add(
                                "env-outside-config", node, qual,
                                f"{d} outside config.py breaks the "
                                "read-at-use knob contract (tests "
                                "monkeypatch config.py; caches key on "
                                "its getters)")

        walk(tree.body, [])
        for fn, fq in fingerprints:
            self._lint_fingerprint(fn, fq)

    # ---- lock discipline (shared-state classes) ----------------------------
    def _scan_locking(self, body, qual: List[str]):
        """Find lock-owning classes at any nesting depth and hold their
        shared-state mutations to the lock (see _LockLinter)."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_locking(stmt.body, qual + [stmt.name])
            elif isinstance(stmt, ast.ClassDef):
                locks: Set[str] = set()
                for node in stmt.body:
                    # class-level lock attribute (`_lock = Lock()`)
                    if isinstance(node, ast.Assign) and \
                            _is_lock_ctor(node.value):
                        locks.update(t.id for t in node.targets
                                     if isinstance(t, ast.Name))
                for node in ast.walk(stmt):
                    # instance lock (`self._lock = Lock()` in any method)
                    if isinstance(node, ast.Assign) and \
                            _is_lock_ctor(node.value):
                        locks.update(
                            _self_attr_of(t) for t in node.targets
                            if _self_attr_of(t))
                locks.discard("")
                if locks:
                    _LockLinter(self, stmt, qual + [stmt.name],
                                locks).run()
                self._scan_locking(stmt.body, qual + [stmt.name])

    # ---- module-global mutation --------------------------------------------
    def _module_locks(self) -> Set[str]:
        """Module-level names bound to threading.Lock()/RLock() — a
        `with <that name>:` counts as a lock regardless of its name."""
        got = getattr(self, "_module_lock_names", None)
        if got is None:
            got = {t.id for stmt in self.tree.body
                   if isinstance(stmt, ast.Assign)
                   and _is_lock_ctor(stmt.value)
                   for t in stmt.targets if isinstance(t, ast.Name)}
            self._module_lock_names = got
        return got

    def _scan_globals(self, body, qual: List[str]):
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                self._scan_globals(stmt.body, qual + [stmt.name])
                continue
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            fq = qual + [stmt.name]
            self._scan_globals(stmt.body, fq)
            declared: Set[str] = set()
            for node in _scope_walk(stmt):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            # *_locked functions are called under the lock by convention
            # — same contract as the lock-discipline rule's method exempt
            self._walk_global_writes(stmt.body, fq, declared,
                                     stmt.name.endswith("_locked"))

    def _walk_global_writes(self, body, qual, names: Set[str],
                            under: bool):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.With):
                locks = self._module_locks()
                inner = under or any(
                    _lockish(item.context_expr)
                    or _dotted(item.context_expr) in locks
                    for item in stmt.items)
                self._walk_global_writes(stmt.body, qual, names, inner)
                continue
            if isinstance(stmt, (ast.If, ast.While, ast.For)):
                self._walk_global_writes(stmt.body, qual, names, under)
                self._walk_global_writes(stmt.orelse, qual, names, under)
                continue
            if isinstance(stmt, ast.Try):
                for b in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk_global_writes(b, qual, names, under)
                for h in stmt.handlers:
                    self._walk_global_writes(h.body, qual, names, under)
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)) and not under:
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    for el in (t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t]):
                        if isinstance(el, ast.Name) and el.id in names:
                            self._add(
                                "global-mutation", stmt, qual,
                                f"module global `{el.id}` reassigned "
                                "outside a lock — two threads racing "
                                "first use both run the initializer "
                                "(double-loaded state for stateful "
                                "singletons); guard with a module lock, "
                                "or allowlist idempotent pure-value "
                                "caches with that justification")

    def _lint_fingerprint(self, fn, qual: List[str]):
        sanctioned: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    _dotted(node.func) == "sorted":
                for a in ast.walk(node):
                    sanctioned.add(id(a))
        for node in ast.walk(fn):
            if id(node) in sanctioned:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("items", "keys", "values"):
                self._add("fingerprint-iteration", node, qual,
                          f".{node.func.attr}() iterated unsorted "
                          "inside a fingerprint computation — dict "
                          "order is insertion order, which is not "
                          "canonical across equivalent plans; wrap in "
                          "sorted()")
            elif isinstance(node, ast.For) and \
                    isinstance(node.iter, ast.Call) and \
                    _dotted(node.iter.func) in ("set", "frozenset"):
                self._add("fingerprint-iteration", node, qual,
                          "iterating a set inside a fingerprint "
                          "computation — set order is nondeterministic "
                          "across processes; sort first")


_MUTATORS = {"append", "add", "update", "setdefault", "pop", "popitem",
             "clear", "extend", "remove", "discard", "insert"}
_LOCK_EXEMPT_METHODS = {"__init__", "__post_init__", "__enter__",
                        "__exit__"}


def _is_lock_ctor(node) -> bool:
    """threading.Lock()/RLock()/Condition() (any dotted prefix).
    Condition counts structurally: `self._cv = threading.Condition(
    self._lock)` names the SAME sync object as the lock it wraps, so
    `with self._cv:` regions are locked evidence for lock-discipline —
    previously only conditions whose NAME matched the _lockish
    heuristic (scheduler.py's `_lock_cond`) were recognized, and a
    condition named `_cv` read as two unrelated sync objects."""
    return (isinstance(node, ast.Call)
            and _dotted(node.func).split(".")[-1] in ("Lock", "RLock",
                                                      "Condition"))


def _self_attr_of(node) -> str:
    """The `Y` of a `self.Y`-rooted expression, peeling subscripts
    (`self._ops[op]` mutates `self._ops`); '' otherwise."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _lockish(expr) -> bool:
    """Whether a with-context expression looks like a lock acquisition:
    any dotted segment containing 'lock' or named '_mu'/'mutex'."""
    d = _dotted(expr).lower()
    return any("lock" in seg or seg in ("_mu", "mu", "mutex")
               for seg in d.split("."))


class _LockLinter:
    """One lock-owning class: collect every mutation of a `self.*`
    attribute with its under-lock state, then flag the INCONSISTENT ones
    — attributes mutated under the class's lock somewhere (that is what
    marks them shared) and without it elsewhere."""

    def __init__(self, module: "_ModuleLinter", cls: ast.ClassDef,
                 qual: List[str], locks: Set[str]):
        self.module = module
        self.cls = cls
        self.qual = qual
        self.locks = locks
        # attr -> list of (locked: bool, node, method qualname)
        self.mutations: Dict[str, List[Tuple[bool, ast.AST, str]]] = {}

    def run(self):
        for stmt in self.cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            exempt = (stmt.name in _LOCK_EXEMPT_METHODS
                      or stmt.name.endswith("_locked"))
            # a *_locked method is called under the lock by convention:
            # its mutations are locked EVIDENCE and never findings
            self._walk(stmt.body, stmt.name,
                       under=stmt.name.endswith("_locked"),
                       flaggable=not exempt)
        protected = {a for a, ms in self.mutations.items()
                     if any(state == "locked" for state, _, _ in ms)}
        for attr, ms in self.mutations.items():
            if attr not in protected:
                continue
            for state, node, meth in ms:
                if state != "unlocked":
                    continue
                self.module._add(
                    "lock-discipline", node, self.qual + [meth],
                    f"`self.{attr}` is lock-protected shared state "
                    f"(mutated under the class's lock elsewhere) but is "
                    "mutated here without holding it — take the lock, or "
                    "rename the method *_locked if every caller already "
                    "holds it")

    def _note(self, target, under: bool, node, meth: str, flaggable: bool):
        attr = _self_attr_of(target)
        if not attr or attr in self.locks:
            return
        # three-state: "locked" is EVIDENCE the attr is shared (and never
        # a finding), "exempt" (__init__ & friends — single-threaded by
        # construction contract) is neither evidence nor a finding,
        # "unlocked" is a finding iff the attr has locked evidence
        state = ("locked" if under
                 else ("unlocked" if flaggable else "exempt"))
        self.mutations.setdefault(attr, []).append((state, node, meth))

    def _walk(self, body, meth: str, under: bool, flaggable: bool):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue            # nested defs: out of scope
            if isinstance(stmt, ast.With):
                inner = under or any(
                    _self_attr_of(item.context_expr) in self.locks
                    or _lockish(item.context_expr)
                    for item in stmt.items)
                self._walk(stmt.body, meth, inner, flaggable)
                continue
            if isinstance(stmt, (ast.If, ast.While, ast.For)):
                self._walk(stmt.body, meth, under, flaggable)
                self._walk(stmt.orelse, meth, under, flaggable)
                continue
            if isinstance(stmt, ast.Try):
                for b in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk(b, meth, under, flaggable)
                for h in stmt.handlers:
                    self._walk(h.body, meth, under, flaggable)
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    for el in (t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t]):
                        self._note(el, under, stmt, meth, flaggable)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._note(t, under, stmt, meth, flaggable)
            # mutating method calls anywhere in the statement
            # (self._ops.setdefault(...), self._q.put(...) is not in the
            # mutator set — queues are internally synchronized)
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS:
                    self._note(node.func.value, under, node, meth,
                               flaggable)


# ---- allowlist --------------------------------------------------------------

def load_allowlist(path: str) -> Dict[Tuple[str, str, str], str]:
    """{(path, rule, context): justification}. Every entry REQUIRES a
    non-empty `# justification`; a bare suppression fails the run."""
    out: Dict[Tuple[str, str, str], str] = {}
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entry, _, just = line.partition("#")
            just = just.strip()
            fields = [p.strip() for p in entry.strip().split("::")]
            if len(fields) != 3 or not all(fields):
                raise SystemExit(
                    f"{path}:{lineno}: malformed allowlist entry "
                    f"(want path::rule::context  # justification)")
            if not just:
                raise SystemExit(
                    f"{path}:{lineno}: allowlist entry for "
                    f"{fields[0]} has no justification — every vetted "
                    "exception must say why")
            out[tuple(fields)] = just
    return out


# ---- driver -----------------------------------------------------------------

def lint_paths(paths: List[str], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, _, names in os.walk(p):
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
    for path in sorted(files):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        with open(path, "rb") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding("parse-error", rel, e.lineno or 0,
                                    "", str(e)))
            continue
        findings.extend(_ModuleLinter(path, rel, tree).run())
    return findings


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(
        description="JAX-hazard linter (docs/analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: spark_rapids_tpu)")
    ap.add_argument("--allowlist",
                    default=os.path.join(repo_root, "tools",
                                         "lint_hazards_allowlist.txt"))
    ap.add_argument("--list", action="store_true",
                    help="print every finding, including allowlisted")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(repo_root, "spark_rapids_tpu")]
    allow = load_allowlist(args.allowlist)
    findings = lint_paths(paths, repo_root)
    used: Set[Tuple[str, str, str]] = set()
    open_findings: List[Finding] = []
    for f in findings:
        if f.key() in allow:
            used.add(f.key())
            if args.list:
                print(f"ALLOWED {f}  # {allow[f.key()]}")
        else:
            open_findings.append(f)
    for f in open_findings:
        print(f)
    stale = set(allow) - used
    for key in sorted(stale):
        # a stale entry is a FAILURE, not a note: it outlived the finding
        # it vetted and now pre-suppresses whatever regresses into the
        # same (path, rule, context) slot next — prune it in the change
        # that fixed the code
        print(f"STALE allowlist entry (matches no finding — prune it): "
              f"{'::'.join(key)}")
    if open_findings or stale:
        print(f"lint_hazards: {len(open_findings)} finding(s), "
              f"{len(stale)} stale allowlist entr(ies) "
              f"({len(used)} allowlisted)")
        return 1
    print(f"lint_hazards: clean ({len(used)} vetted exception(s), "
          f"{len(findings)} raw finding(s))")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
