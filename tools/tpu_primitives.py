"""On-chip primitive cost measurement for the relational-core redesign.

The groupby/join kernels are compositions of lax.sort, cumsum,
associative_scan, gather (jnp.take), scatter (.at[].set/.add), jnp.repeat
and searchsorted. docs/architecture.md carries one round of these numbers
(10M rows: sort 38ms, cumsum 16ms, gather 160ms, scatter-add-x64 930ms,
searchsorted 2s); this tool re-measures them with the validated barrier
methodology (benchmarks.common), sweeps the axes that drive the round-3
design decisions, and prints one JSON line per measurement:

- marginal cost of a sort OPERAND (payload-through-sort vs gather-after):
  sort with 1..6 operands, u32 vs emulated-i64 keys;
- gather: random vs monotone indices, 4B vs 8B elements;
- scatter: .at[].set vs .add, random vs sorted+unique indices (the
  indices_are_sorted/unique_indices flags), i32 vs i64;
- scans: cumsum over i32/i64/f32/f64, tuple-carry associative_scan
  (the segmented-reduce workhorse), jnp.repeat expansion;
- MXU calibration: big i8xi8->i32 and bf16 matmul rates (the one-hot
  groupby fast-path budget).

Usage: python tools/tpu_primitives.py [--n 10000000] [--cpu] [--iters 5]
Writes records to stdout and (by default) appends to tools/primitives.jsonl.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000_000)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "primitives.jsonl"))
    ap.add_argument("--only", default=None,
                    help="comma-separated name filter (substring match)")
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    # the package runs under x64 (enabled on import); measure the same regime
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np
    from benchmarks.common import steady_state_ms, sync

    n = args.n
    platform = jax.default_backend()
    rng = np.random.default_rng(0)
    results = []

    def rec(name, ms, note=""):
        r = {"name": name, "n": n, "ms": round(ms, 3), "backend": platform}
        if getattr(steady_state_ms, "last_upper_bound", False):
            r["ms_upper_bound"] = True
        if note:
            r["note"] = note
        print(json.dumps(r), flush=True)
        results.append(r)

    def bench(name, fn, *arrs, note=""):
        if args.only and not any(s in name for s in args.only.split(",")):
            return
        f = jax.jit(fn)
        try:
            t0 = time.perf_counter()
            out = f(*arrs)
            sync(out)
            compile_s = time.perf_counter() - t0
            ms = steady_state_ms(f, arrs, args.iters, platform)
            rec(name, ms, note=note or f"compile {compile_s:.1f}s")
        except Exception as e:  # keep sweeping on a single failure
            print(json.dumps({"name": name, "n": n, "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)

    u32 = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    u32b = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    i64 = jnp.asarray(rng.integers(-2**62, 2**62, size=n, dtype=np.int64))
    i32 = jnp.asarray(rng.integers(-2**31, 2**31, size=n, dtype=np.int32))
    f32 = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    iota = jnp.arange(n, dtype=jnp.int32)
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    sorted_idx = jnp.sort(jnp.asarray(
        rng.integers(0, n, size=n, dtype=np.int32)))

    import jax.lax as lax

    # ---- sort: marginal operand cost ------------------------------------
    bench("sort_k1_u32", lambda a: lax.sort([a], num_keys=1)[0], u32)
    bench("sort_k1_u32_stable",
          lambda a, b: lax.sort([a, b], num_keys=1, is_stable=True)[0],
          u32, iota)
    bench("sort_k1_u32_p1",
          lambda a, b: lax.sort([a, b], num_keys=1)[0], u32, iota)
    bench("sort_k1_u32_p2",
          lambda a, b, c: lax.sort([a, b, c], num_keys=1)[0],
          u32, iota, i32)
    bench("sort_k1_u32_p4",
          lambda a, b, c, d, e: lax.sort([a, b, c, d, e], num_keys=1)[0],
          u32, iota, i32, f32, u32b)
    bench("sort_k1_u32_p4_i64pay",
          lambda a, b, c, d: lax.sort([a, b, c, d], num_keys=1)[0],
          u32, iota, i64, i64)
    bench("sort_k2_u32_p1",
          lambda a, b, c: lax.sort([a, b, c], num_keys=2, is_stable=True)[0],
          u32, u32b, iota)
    bench("sort_k1_i64_p1",
          lambda a, b: lax.sort([a, b], num_keys=1, is_stable=True)[0],
          i64, iota)

    # ---- gather ---------------------------------------------------------
    bench("gather_i32_random", lambda x, ix: jnp.take(x, ix, axis=0),
          i32, perm)
    bench("gather_i32_monotone", lambda x, ix: jnp.take(x, ix, axis=0),
          i32, sorted_idx)
    bench("gather_i64_random", lambda x, ix: jnp.take(x, ix, axis=0),
          i64, perm)
    bench("gather_f32_random", lambda x, ix: jnp.take(x, ix, axis=0),
          f32, perm)

    # ---- scatter --------------------------------------------------------
    bench("scatter_set_i32_random",
          lambda ix, v: jnp.zeros((n,), jnp.int32).at[ix].set(v), perm, i32)
    bench("scatter_set_i32_sorted_unique",
          lambda v: jnp.zeros((n,), jnp.int32).at[iota].set(
              v, indices_are_sorted=True, unique_indices=True), i32)
    bench("scatter_set_i32_monotone",
          lambda ix, v: jnp.zeros((n,), jnp.int32).at[ix].set(
              v, indices_are_sorted=True), sorted_idx, i32)
    bench("scatter_add_i32_random",
          lambda ix, v: jnp.zeros((n,), jnp.int32).at[ix].add(v), perm, i32)
    bench("scatter_add_i64_random",
          lambda ix, v: jnp.zeros((n,), jnp.int64).at[ix].add(v), perm, i64)

    # ---- scans ----------------------------------------------------------
    bench("cumsum_i32", lambda x: jnp.cumsum(x), i32)
    bench("cumsum_i64", lambda x: jnp.cumsum(x.astype(jnp.int64)), i32)
    bench("cumsum_f32", lambda x: jnp.cumsum(x), f32)
    bench("cumsum_f64", lambda x: jnp.cumsum(x.astype(jnp.float64)), f32)

    boundary = jnp.asarray(rng.random(n) < 0.01)

    def segscan_i64(b, v):
        def combine(x, y):
            xb, xv = x
            yb, yv = y
            return xb | yb, jnp.where(yb, yv, xv + yv)
        return lax.associative_scan(combine, (b, v.astype(jnp.int64)))[1]

    bench("segscan_tuple_i64", segscan_i64, boundary, i32)

    def segscan_f64(b, v):
        def combine(x, y):
            xb, xv = x
            yb, yv = y
            return xb | yb, jnp.where(yb, yv, xv + yv)
        return lax.associative_scan(combine, (b, v.astype(jnp.float64)))[1]

    bench("segscan_tuple_f64", segscan_f64, boundary, f32)

    # ---- expansion / search ---------------------------------------------
    counts = jnp.asarray(rng.integers(0, 3, size=n, dtype=np.int32))
    bench("repeat_total_n",
          lambda c: jnp.repeat(iota, c, total_repeat_length=n), counts,
          note="jnp.repeat with static total")
    small = jnp.sort(u32[:4096])
    bench("searchsorted_4096", lambda q: jnp.searchsorted(small, q), u32,
          note="range-partition bucket map")

    # broadcast-compare bucketing: n x 256 compare-reduce (the searchsorted
    # substitute for 256 splitters)
    spl = jnp.sort(u32[:256])
    bench("bucket256_compare",
          lambda q: jnp.sum(q[:, None] >= spl[None, :], axis=1), u32)

    # ---- MXU calibration -------------------------------------------------
    m = 4096
    a8 = jnp.asarray(rng.integers(-127, 127, (m, m), dtype=np.int8))
    b8 = jnp.asarray(rng.integers(-127, 127, (m, m), dtype=np.int8))
    bench("matmul_i8_4096",
          lambda a, b: lax.dot_general(
              a, b, (((1,), (0,)), ((), ())),
              preferred_element_type=jnp.int32), a8, b8,
          note=f"{2 * m**3 / 1e9:.0f} GMAC")
    abf = jnp.asarray(rng.standard_normal((m, m)).astype(np.float32)).astype(jnp.bfloat16)
    bench("matmul_bf16_4096",
          lambda a, b: lax.dot_general(
              a, b, (((1,), (0,)), ((), ())),
              preferred_element_type=jnp.float32), abf, abf)

    if args.out:
        with open(args.out, "a") as f:
            for r in results:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
