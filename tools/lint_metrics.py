#!/usr/bin/env python
"""Bench-JSONL stamp linter (docs/analysis.md): the ROADMAP cross-cutting
rule — `backend`/`n_devices`/`kernels` stamped on EVERY bench record —
made premerge-enforced instead of review-enforced. The bench trajectory
has silently compared CPU-fallback runs against device runs, and kernel
backends against each other, before; a headline number missing any of
those stamps is not comparable to anything.

Two AST rules over ``benchmarks/`` and ``bench.py``:

- ``missing-kernels-stamp``: every ``emit_record(...)`` / ``run_config(
  ...)`` call site must pass ``kernels=`` explicitly. ``backend`` and
  ``n_devices`` are stamped inside ``emit_record`` itself (checked by the
  third rule), but the kernel choices a run dispatched are only knowable
  at the call site — from the executed plan's per-op stamps
  (``nds_plans.kernels_of``), the registry floor
  (``common.registry_kernels``), or the literal ``"fallback"`` for a
  bench that never crosses the registry (bench.py's convention: stamping
  the registry summary would attribute kernels the run never ran).
- ``missing-wire-bytes-stamp``: a call that stamps ``exchange_bytes=``
  must also stamp ``exchange_bytes_wire=`` and
  ``exchange_bytes_logical=`` (plan/transport.py split the legacy
  counter into wire vs logical; a wire number silently compared against
  a logical one is the same class of trajectory bug as a missing
  backend stamp).
- ``missing-session-stamp``: a call that stamps ``queue_wait_ms=`` or
  ``cache_hit=`` must also stamp ``session=`` (serving-layer records,
  docs/serving.md: a queue wait or a cache-served number without its
  tenant session is not attributable — and a cached row measured no
  execution at all, so consumers must be able to filter it).
- ``missing-worker-id-stamp``: a call that stamps ``replays=`` (a
  fleet-layer record, serving/fleet.py) must also stamp ``worker_id=``
  — a fleet completion without the worker that served it cannot be
  attributed across the failover/replay trajectory the number exists
  to describe (docs/serving.md#fleet).
- ``missing-respawn-stamp``: a call that stamps ``respawns=`` (a
  self-healing record, serving/fleet.py) must also stamp ``worker_id=``
  — a respawn count that does not name the replacement worker cannot
  be joined against the membership change it claims happened
  (docs/serving.md#fleet-self-healing).
- ``missing-placement-stamp``: a call that stamps
  ``placement_overlap_ms=`` or ``placement=`` (co-placement records,
  plan/optimizer.py placement rule, docs/optimizer.md#placement) must
  also stamp ``backend=`` and ``session=`` — an overlap number is a
  host-vs-device comparison by construction, so a row that does not
  say which device backend the overlapped walk ran on (or which tenant
  it ran for, "" outside serving) cannot be compared across the
  placement on/off trajectory it exists to describe.
- ``raw-jsonl-missing-stamp``: a ``json.dumps({...literal...})`` record
  must carry ``"backend"`` and ``"kernels"`` keys — unless it carries an
  ``"error"`` key (failure records describe infrastructure, not
  measurements). Dynamic (non-literal) dicts are out of static reach and
  skipped; route them through ``emit_record`` instead.

Definition sites (``benchmarks/common.py``) are exempt from the call-site
rule — ``run_config`` forwards to ``emit_record``, which owns the
backend/n_devices stamping this linter's third check pins down:

- ``emit-record-owns-backend``: ``emit_record``'s body must assign the
  ``"backend"`` and ``"n_devices"`` keys — the auto-stamp every other
  rule leans on must not silently disappear.

Usage::

    python tools/lint_metrics.py [paths...]

Exit status 1 when any finding remains. No allowlist: every record can
and must be stamped.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import List

_RECORD_FNS = {"emit_record", "run_config"}
_EXEMPT_FILES = {"benchmarks/common.py"}


def _last_seg(func) -> str:
    while isinstance(func, ast.Attribute):
        return func.attr
    return func.id if isinstance(func, ast.Name) else ""


def _lint_file(path: str, rel: str, findings: List[str]) -> None:
    with open(path, "rb") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            findings.append(f"{rel}:{e.lineno}: [parse-error] {e}")
            return
    exempt_calls = rel in _EXEMPT_FILES
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _last_seg(node.func)
        if name in _RECORD_FNS and not exempt_calls:
            kw = {k.arg for k in node.keywords}
            if "kernels" not in kw:
                findings.append(
                    f"{rel}:{node.lineno}: [missing-kernels-stamp] "
                    f"{name}() without kernels= — stamp the kernel "
                    "choices the measured run actually dispatched "
                    "(kernels_of(res) for plan benches, "
                    "registry_kernels(...) for registry-op benches, "
                    "\"fallback\" for registry-free ones)")
            if "exchange_bytes" in kw and \
                    not {"exchange_bytes_wire",
                         "exchange_bytes_logical"} <= kw:
                findings.append(
                    f"{rel}:{node.lineno}: [missing-wire-bytes-stamp] "
                    f"{name}() stamps exchange_bytes without "
                    "exchange_bytes_wire/exchange_bytes_logical — a "
                    "wire number silently compared against a logical "
                    "one is not comparable (plan/transport.py, "
                    "docs/distributed.md#transport)")
            if kw & {"queue_wait_ms", "cache_hit"} and "session" not in kw:
                findings.append(
                    f"{rel}:{node.lineno}: [missing-session-stamp] "
                    f"{name}() stamps queue_wait_ms/cache_hit without "
                    "session= — a serving-layer number without its "
                    "tenant session is not attributable "
                    "(serving/scheduler.py, docs/serving.md)")
            if "replays" in kw and "worker_id" not in kw:
                findings.append(
                    f"{rel}:{node.lineno}: [missing-worker-id-stamp] "
                    f"{name}() stamps replays= without worker_id= — a "
                    "fleet-layer completion without the worker that "
                    "served it is not attributable across failover "
                    "(serving/fleet.py, docs/serving.md#fleet)")
            if kw & {"placement_overlap_ms", "placement"} and \
                    not {"backend", "session"} <= kw:
                findings.append(
                    f"{rel}:{node.lineno}: [missing-placement-stamp] "
                    f"{name}() stamps placement/placement_overlap_ms "
                    "without backend= and session= — a co-placement "
                    "overlap number without the device backend it "
                    "overlapped (and its tenant session, \"\" outside "
                    "serving) is not comparable across the placement "
                    "on/off trajectory (plan/optimizer.py, "
                    "docs/optimizer.md#placement)")
            if "respawns" in kw and "worker_id" not in kw:
                findings.append(
                    f"{rel}:{node.lineno}: [missing-respawn-stamp] "
                    f"{name}() stamps respawns= without worker_id= — a "
                    "self-healing record that does not name the "
                    "replacement worker cannot be joined against the "
                    "respawn it claims happened "
                    "(serving/fleet.py, docs/serving.md#fleet-self-healing)")
        elif name == "dumps" and node.args and \
                isinstance(node.args[0], ast.Dict):
            keys = {k.value for k in node.args[0].keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if "error" in keys:
                continue        # failure record, not a measurement
            missing = {"backend", "kernels"} - keys
            if missing:
                findings.append(
                    f"{rel}:{node.lineno}: [raw-jsonl-missing-stamp] "
                    f"json.dumps record lacks {sorted(missing)} — every "
                    "measurement row carries backend/n_devices/kernels "
                    "(route it through emit_record, which auto-stamps "
                    "backend and n_devices)")


def _check_emit_record(root: str, findings: List[str]) -> None:
    path = os.path.join(root, "benchmarks", "common.py")
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "emit_record":
            assigned = {t.slice.value
                        for stmt in ast.walk(node)
                        if isinstance(stmt, ast.Assign)
                        for t in stmt.targets
                        if isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)}
            # the initial dict literal counts too
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    assigned |= {k.value for k in sub.keys
                                 if isinstance(k, ast.Constant)}
            missing = {"backend", "n_devices"} - assigned
            if missing:
                findings.append(
                    f"benchmarks/common.py:{node.lineno}: "
                    f"[emit-record-owns-backend] emit_record no longer "
                    f"stamps {sorted(missing)} — every downstream rule "
                    "leans on this auto-stamp")
            return
    findings.append("benchmarks/common.py: [emit-record-owns-backend] "
                    "emit_record not found")


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(
        description="bench-JSONL stamp linter (docs/analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: benchmarks/ and "
                         "bench.py)")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(repo_root, "benchmarks"),
                           os.path.join(repo_root, "bench.py")]
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, _, names in os.walk(p):
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
    findings: List[str] = []
    for path in sorted(files):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        _lint_file(path, rel, findings)
    _check_emit_record(repo_root, findings)
    for f in findings:
        print(f)
    if findings:
        print(f"lint_metrics: {len(findings)} finding(s)")
        return 1
    print(f"lint_metrics: clean ({len(files)} file(s))")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
