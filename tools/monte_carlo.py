"""Randomized task/allocation stress harness for the resource arbiter.

TPU-native equivalent of the reference's RmmSparkMonteCarlo
(/root/reference/src/test/java/com/nvidia/spark/rapids/jni/RmmSparkMonteCarlo.java,
SURVEY.md §4 tier 3): generate random "situations" — tasks issuing skewed
sequences of reserve/release ops, run them on a bounded worker pool (plus a
shuffle thread pool) against a small device budget, and measure completion,
retry/split counts, blocked time and wall clock. `--baseline` runs the same
situations WITHOUT the arbiter (plain bounded budget with timed waits) so the
two can be compared, exactly like the reference's `--baseline` mode.

Run nightly by ci/fuzz-test.sh. Example:

    python tools/monte_carlo.py --tasks 64 --parallelism 8 \
        --gpu-mib 3072 --task-max-mib 2048 --skewed
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import List

sys.path.insert(0, ".")

# This is a host-side state-machine fuzzer: it never launches device work, so
# pin the CPU backend before anything can initialize an accelerator (a dead
# device tunnel would otherwise hang the whole harness at backend init).
import os  # noqa: E402
import jax  # noqa: E402
jax.config.update("jax_platforms", os.environ.get("SRT_MC_PLATFORM", "cpu"))

from spark_rapids_tpu.runtime import (DeviceSession, HardOOM,  # noqa: E402
                                      Reservation, ResourceArbiter, with_retry)

MIB = 1024 * 1024


# ---- situation generation (reference generateSituations) --------------------

@dataclass
class AllocOp:
    size: int          # bytes

@dataclass
class FreeOp:
    index: int         # which live buffer to free (mod len)

@dataclass
class OpSet:
    ops: List[object]
    is_shuffle: bool = False
    sleep_ms: int = 0

@dataclass
class TaskSpec:
    task_id: int
    op_sets: List[OpSet] = field(default_factory=list)


def generate_tasks(rng: random.Random, n_tasks: int, task_max_bytes: int,
                   max_allocs: int, max_sleep_ms: int, skewed: bool,
                   skew_amount: float, shuffle: bool) -> List[TaskSpec]:
    tasks = []
    for t in range(n_tasks):
        # skew: a few tasks allocate close to the whole task budget, most are
        # small (reference --skewed / --skewAmount)
        scale = 1.0
        if skewed and rng.random() < 0.2:
            scale = 1.0 + skew_amount
        spec = TaskSpec(task_id=t)
        for _ in range(rng.randint(1, 4)):
            ops: List[object] = []
            live = 0
            for _ in range(rng.randint(1, max_allocs)):
                if live and rng.random() < 0.4:
                    ops.append(FreeOp(rng.randrange(live)))
                    live -= 1
                else:
                    frac = rng.random() ** 2  # bias small
                    size = max(4096, int(task_max_bytes * frac * scale / max_allocs))
                    ops.append(AllocOp(size))
                    live += 1
            is_shuf = shuffle and rng.random() < 0.25
            ops_sleep = rng.randint(0, max_sleep_ms)
            spec.op_sets.append(OpSet(ops, is_shuffle=is_shuf, sleep_ms=ops_sleep))
        tasks.append(spec)
    return tasks


# ---- arbitrated run ---------------------------------------------------------

@dataclass
class Stats:
    completed: int = 0
    failed: int = 0
    retries: int = 0
    split_retries: int = 0
    blocked_ns: int = 0
    lost_ns: int = 0
    wall_s: float = 0.0

    def as_json(self, mode: str) -> str:
        return json.dumps({"mode": mode, **self.__dict__})


def run_op_set(session: DeviceSession, op_set: OpSet, buffers: List[Reservation],
               split_level: int = 0):
    """Execute one op-set's allocs/frees under the retry protocol."""
    arb = session.arbiter

    def attempt(divisor: int):
        acquired: List[Reservation] = []
        try:
            for op in op_set.ops:
                if isinstance(op, AllocOp):
                    acquired.append(session.device.acquire(max(op.size // divisor, 1)))
                else:
                    pool = buffers if buffers else acquired
                    if pool:
                        session.device.release(pool.pop(op.index % len(pool)))
            if op_set.sleep_ms:
                time.sleep(op_set.sleep_ms / 1e3)
        except BaseException:
            for r in acquired:
                session.device.release(r)
            raise
        return acquired

    def rollback():
        # make state "spillable": free everything this task currently holds
        while buffers:
            session.device.release(buffers.pop())

    # SplitAndRetry = split the op set into two halves, each with every
    # allocation halved (divisor doubles per split level)
    results = with_retry(arb, attempt, 1,
                         split=lambda d: [d * 2, d * 2],
                         on_rollback=rollback)
    for acquired in results:
        buffers.extend(acquired)


def run_arbitrated(tasks: List[TaskSpec], parallelism: int, gpu_bytes: int,
                   shuffle_threads: int, task_retry: int) -> Stats:
    stats = Stats()
    mu = threading.Lock()
    t0 = time.perf_counter()
    with DeviceSession(device_limit_bytes=gpu_bytes) as session:
        arb = session.arbiter
        shuffle_pool = ThreadPoolExecutor(max_workers=max(shuffle_threads, 1))

        def run_task(spec: TaskSpec):
            arb.current_thread_is_dedicated_to_task(spec.task_id)
            buffers: List[Reservation] = []
            ok = False
            try:
                for attempt_no in range(task_retry + 1):
                    try:
                        for op_set in spec.op_sets:
                            if op_set.is_shuffle:
                                def shuf(op_set=op_set):
                                    arb.shuffle_thread_working_on_tasks([spec.task_id])
                                    sbuf: List[Reservation] = []
                                    try:
                                        run_op_set(session, op_set, sbuf)
                                    finally:
                                        while sbuf:
                                            session.device.release(sbuf.pop())
                                        arb.pool_thread_finished_for_tasks([spec.task_id])
                                arb.submitting_to_pool()
                                fut = shuffle_pool.submit(shuf)
                                try:
                                    fut.result()
                                finally:
                                    arb.done_waiting_on_pool()
                            else:
                                run_op_set(session, op_set, buffers)
                        ok = True
                        break
                    except HardOOM:
                        # roll everything back and retry the task from scratch
                        while buffers:
                            session.device.release(buffers.pop())
            finally:
                while buffers:
                    session.device.release(buffers.pop())
                with mu:
                    stats.retries += arb.get_and_reset_num_retry_throw(spec.task_id)
                    stats.split_retries += arb.get_and_reset_num_split_retry_throw(spec.task_id)
                    stats.blocked_ns += arb.get_and_reset_block_time_ns(spec.task_id)
                    stats.lost_ns += arb.get_and_reset_computation_time_lost_ns(spec.task_id)
                    if ok:
                        stats.completed += 1
                    else:
                        stats.failed += 1
                arb.task_done(spec.task_id)

        with ThreadPoolExecutor(max_workers=parallelism) as pool:
            futs = [pool.submit(run_task, spec) for spec in tasks]
            for f in futs:
                f.result()
        shuffle_pool.shutdown(wait=True)
    stats.wall_s = round(time.perf_counter() - t0, 3)
    return stats


# ---- baseline (no arbiter) --------------------------------------------------

class PlainBudget:
    """Bounded budget with timed condition waits — what you get WITHOUT the
    arbiter: no priorities, no deadlock detection, no retry protocol."""

    def __init__(self, limit: int, timeout_s: float = 2.0):
        self.limit = limit
        self.used = 0
        self.cv = threading.Condition()
        self.timeout_s = timeout_s

    def acquire(self, n: int) -> int:
        deadline = time.monotonic() + self.timeout_s
        with self.cv:
            while self.used + n > self.limit:
                left = deadline - time.monotonic()
                if left <= 0 or not self.cv.wait(timeout=left):
                    raise HardOOM("baseline allocation timed out (possible deadlock)")
            self.used += n
        return n

    def release(self, n: int):
        with self.cv:
            self.used -= n
            self.cv.notify_all()


def run_baseline(tasks: List[TaskSpec], parallelism: int, gpu_bytes: int,
                 task_retry: int) -> Stats:
    stats = Stats()
    mu = threading.Lock()
    budget = PlainBudget(gpu_bytes)
    t0 = time.perf_counter()

    def run_task(spec: TaskSpec):
        held: List[int] = []
        ok = False
        try:
            for _ in range(task_retry + 1):
                try:
                    for op_set in spec.op_sets:
                        for op in op_set.ops:
                            if isinstance(op, AllocOp):
                                held.append(budget.acquire(op.size))
                            elif held:
                                budget.release(held.pop(op.index % len(held)))
                        if op_set.sleep_ms:
                            time.sleep(op_set.sleep_ms / 1e3)
                    ok = True
                    break
                except HardOOM:
                    while held:
                        budget.release(held.pop())
        finally:
            while held:
                budget.release(held.pop())
            with mu:
                if ok:
                    stats.completed += 1
                else:
                    stats.failed += 1

    with ThreadPoolExecutor(max_workers=parallelism) as pool:
        futs = [pool.submit(run_task, spec) for spec in tasks]
        for f in futs:
            f.result()
    stats.wall_s = round(time.perf_counter() - t0, 3)
    return stats


# ---- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iterations", type=int, default=1)
    ap.add_argument("--tasks", type=int, default=32)
    ap.add_argument("--parallelism", type=int, default=8)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--gpu-mib", type=int, default=3072,
                    help="device budget MiB (name kept for reference parity)")
    ap.add_argument("--task-max-mib", type=int, default=2048)
    ap.add_argument("--task-retry", type=int, default=2)
    ap.add_argument("--max-task-allocs", type=int, default=8)
    ap.add_argument("--max-task-sleep", type=int, default=2, help="ms")
    ap.add_argument("--shuffle-threads", type=int, default=2)
    ap.add_argument("--skewed", action="store_true")
    ap.add_argument("--skew-amount", type=float, default=2.0)
    ap.add_argument("--baseline", action="store_true",
                    help="also run without the arbiter and compare")
    args = ap.parse_args(argv)

    seed = args.seed if args.seed is not None else random.randrange(2**31)
    print(json.dumps({"seed": seed, "tasks": args.tasks,
                      "parallelism": args.parallelism,
                      "gpu_mib": args.gpu_mib, "task_max_mib": args.task_max_mib}))
    failures = 0
    for it in range(args.iterations):
        rng = random.Random(seed + it)
        tasks = generate_tasks(rng, args.tasks, args.task_max_mib * MIB,
                               args.max_task_allocs, args.max_task_sleep,
                               args.skewed, args.skew_amount,
                               shuffle=args.shuffle_threads > 0)
        st = run_arbitrated(tasks, args.parallelism, args.gpu_mib * MIB,
                            args.shuffle_threads, args.task_retry)
        print(st.as_json("arbitrated"))
        if st.failed:
            failures += st.failed
        if args.baseline:
            sb = run_baseline(tasks, args.parallelism, args.gpu_mib * MIB,
                              args.task_retry)
            print(sb.as_json("baseline"))
    # the arbitrated run must complete every task; that's the whole point
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
