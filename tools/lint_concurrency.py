#!/usr/bin/env python
"""Static concurrency linter: lock-order graph, blocking-under-lock,
worker isolation (docs/analysis.md#concurrency-invariants).

The serving stack is four threaded layers deep — fleet router →
scheduler dispatchers → worker executors → health/stats/cache — with
~30 distinct ``threading.Lock``/``RLock``/``Condition`` sites. Their
ordering and isolation invariants were previously enforced only by
review; this tool machine-checks them premerge (ci/premerge.sh), the
way lint_hazards checks JAX hazards:

- ``lock-order-cycle``: the whole-tree LOCK GRAPH — every lock
  attribute/module lock, keyed per (module, class, attr) like kernel
  lockdep's lock classes, with an edge A→B wherever B is acquired
  while A is held, resolved INTERPROCEDURALLY through self-method,
  typed-attribute, module-function, and constructor calls. Any cycle
  is a potential deadlock; the finding prints the witness path (which
  function, which line, through which call chain, closes each edge).
- ``blocking-under-lock``: an unbounded wait reached while a lock is
  held — ``Condition``/``Event.wait()`` without timeout, ``.join()``
  without timeout, ``queue.Queue.get/put`` without timeout,
  ``.result()`` without timeout, ``PlanExecutor.execute`` — directly
  or through a call chain. Waiting on a condition while holding ONLY
  that condition's own lock is exempt (wait releases it); holding any
  OTHER lock across an unbounded wait stalls every thread that needs
  it. Bounded waits (timeout slices, ``join(timeout=...)``) pass.
- ``worker-isolation``: ``FleetWorker``-owned mutable state (executor,
  health monitor, stats store, the scheduler's cache/queue internals)
  must only be reached via its owning worker. Outside FleetWorker
  itself, the router may touch a worker's ``id``/``alive``/
  ``pressure_score`` and call ``scheduler.open_session/close/metrics/
  pressure`` — anything else (``w.executor``, ``w.stats``,
  ``w.health``, ``w.scheduler.cache``, a bare ``w.scheduler`` escaping)
  is a cross-worker reach. The invalidation bus and the
  ``peek_frozen``/``adopt`` promotion path are the two sanctioned
  exceptions, carried in the allowlist with justifications.

The lock graph this tool extracts is also the SHARED EDGE VOCABULARY
for the runtime lockdep witness (spark_rapids_tpu/runtime/lockdep.py,
``SPARK_RAPIDS_TPU_LOCKDEP=1``): ``--emit-graph`` dumps
``{locks: {name: "path:line"}, edges: [[a, b], ...]}`` where the site
is the lock's construction line, so a dynamically observed
held→acquired edge maps back to its static prediction and any dynamic
edge the static graph missed is reported as divergence — the
interprocedural resolution is empirically auditable. Call targets the
resolver cannot identify add no edges (an under-approximation, audited
by exactly that divergence check); edges the analysis cannot derive but
the witness proves real are declared in the allowlist as::

    edge::<lock-name> -> <lock-name>  # justification

Declared edges join the cycle check (a declared edge completing a
cycle FAILS) and the emitted graph. Same-name self-edges are excluded
from the graph on both halves: the only same-class nesting in the tree
is RLock reentrancy on one instance, and a class-keyed self-edge
cannot distinguish that from a real two-instance deadlock.

Vetted exceptions live in the allowlist (default
``tools/lint_concurrency_allowlist.txt``), one per line::

    <repo/relative/path.py>::<rule>::<qualified.context>  # justification

The justification is REQUIRED, and a STALE entry (matching no current
finding) FAILS the run — same policy as lint_hazards. Usage::

    python tools/lint_concurrency.py [paths...] [--allowlist FILE]
                                     [--list] [--emit-graph FILE]

Exit status 1 when any unsuppressed finding remains, or any allowlist
entry has gone stale.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTOR = "Condition"

# worker-isolation policy: class name -> (plain read surface,
# {gateway attr: allowed methods through it}, owned mutable state)
_ISOLATION = {
    "FleetWorker": {
        # draining + the gossip/trip wrappers are the self-healing
        # surface (serving/fleet.py): cross-worker stats exchange and
        # trip attribution go through the worker's OWN methods, never
        # through raw reaches into its stats/health internals
        "surface": {"id", "alive", "draining", "pressure_score",
                    "drain_trips", "gossip_export", "gossip_merge"},
        "via": {"scheduler": {"open_session", "close", "metrics",
                              "pressure"}},
        "owned": {"executor", "stats", "health"},
    },
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    context: str         # dotted qualname of the enclosing def/class
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.context)

    def __str__(self):
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.context or '<module>'}: {self.message}")


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _short(lock_name: str) -> str:
    """Compact lock name for witness paths / allowlist contexts:
    'spark_rapids_tpu/serving/fleet.py:FleetScheduler._lock' ->
    'serving/fleet:FleetScheduler._lock'."""
    path, _, rest = lock_name.partition(":")
    if path.endswith(".py"):
        path = path[:-3]
    if path.startswith("spark_rapids_tpu/"):
        path = path[len("spark_rapids_tpu/"):]
    return f"{path}:{rest}"


# ---- model ------------------------------------------------------------------

class LockDecl:
    """One lock CLASS (lockdep's sense): a (module, owner, attr) slot,
    not an instance. `site` is the construction line — the dynamic
    witness keys wrapped locks by construction site, which is how both
    halves share one vocabulary."""

    def __init__(self, name: str, rel: str, line: int, kind: str):
        self.name = name
        self.rel = rel
        self.line = line
        self.kind = kind                   # "lock" | "rlock" | "condition"

    @property
    def site(self) -> str:
        return f"{self.rel}:{self.line}"


class ClassInfo:
    def __init__(self, rel: str, name: str):
        self.rel = rel
        self.name = name
        self.key = f"{rel}:{name}"
        self.locks: Dict[str, LockDecl] = {}       # attr -> decl
        self.aliases: Dict[str, str] = {}          # Condition attr -> lock attr
        self.attr_types: Dict[str, tuple] = {}     # attr -> TypeRef
        self.methods: Dict[str, "FuncInfo"] = {}


class FuncInfo:
    def __init__(self, rel: str, qual: str, node, cls: Optional[ClassInfo],
                 mod: "ModuleInfo"):
        self.rel = rel
        self.qual = qual
        self.node = node
        self.cls = cls
        self.mod = mod
        self.param_types: Dict[str, tuple] = {}
        self.ret_type: Optional[tuple] = None
        self.locals_funcs: Dict[str, "FuncInfo"] = {}  # nested defs
        # filled by the scan pass:
        self.acquires: Set[str] = set()            # direct lock names
        self.calls: Set["FuncInfo"] = set()        # every resolved callee
        self.blocking: List[tuple] = []            # (line, desc, own_lock)
        self.under: List[tuple] = []   # (held names, line, callee, blockdesc)
        self.local_edges: List[tuple] = []         # (src, dst, line)


class ModuleInfo:
    def __init__(self, rel: str, tree):
        self.rel = rel
        self.tree = tree
        self.classes: Dict[str, ClassInfo] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.imports: Dict[str, tuple] = {}   # local -> ("mod",rel)|("sym",rel,name)
        self.module_locks: Dict[str, LockDecl] = {}
        self.var_types: Dict[str, tuple] = {}


class Model:
    """Whole-tree index: modules, lock declarations, resolution tables."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self.class_by_key: Dict[str, ClassInfo] = {}
        self.funcs_by_name: Dict[str, List[FuncInfo]] = {}
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        self.locks: Dict[str, LockDecl] = {}
        self.findings: List[Finding] = []
        # edge -> (rel, line, qual, note): first witness wins
        self.edges: Dict[Tuple[str, str], tuple] = {}
        self._trans_acq: Dict[int, Set[str]] = {}
        self._trans_blk: Dict[int, List[tuple]] = {}

    # -- construction ---------------------------------------------------------

    def add_module(self, rel: str, tree) -> ModuleInfo:
        mod = ModuleInfo(rel, tree)
        self.modules[rel] = mod
        return mod

    def index(self):
        for mod in self.modules.values():
            for ci in mod.classes.values():
                self.classes_by_name.setdefault(ci.name, []).append(ci)
                self.class_by_key[ci.key] = ci
                for decl in ci.locks.values():
                    self.locks[decl.name] = decl
                for fi in ci.methods.values():
                    self.methods_by_name.setdefault(
                        fi.node.name, []).append(fi)
            for fi in mod.funcs.values():
                self.funcs_by_name.setdefault(fi.node.name, []).append(fi)
            for decl in mod.module_locks.values():
                self.locks[decl.name] = decl

    # -- resolution -----------------------------------------------------------

    def resolve_class(self, mod: ModuleInfo, name: str) -> Optional[ClassInfo]:
        ci = mod.classes.get(name)
        if ci is not None:
            return ci
        imp = mod.imports.get(name)
        if imp is not None and imp[0] == "sym":
            target = self.modules.get(imp[1])
            if target is not None:
                ci = target.classes.get(imp[2])
                if ci is not None:
                    return ci
        cands = self.classes_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def resolve_func(self, mod: ModuleInfo, name: str) -> Optional[FuncInfo]:
        fi = mod.funcs.get(name)
        if fi is not None:
            return fi
        imp = mod.imports.get(name)
        if imp is not None and imp[0] == "sym":
            target = self.modules.get(imp[1])
            if target is not None:
                fi = target.funcs.get(imp[2])
                if fi is not None:
                    return fi
        cands = self.funcs_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def resolve_in_alias(self, mod: ModuleInfo, alias: str, name: str):
        """`cache_mod.ResultCache` / `cache_mod.input_digest` through a
        module import alias: -> ("class", ci) | ("func", fi) | None."""
        imp = mod.imports.get(alias)
        if imp is None or imp[0] != "mod":
            return None
        target = self.modules.get(imp[1])
        if target is None:
            return None
        if name in target.classes:
            return ("class", target.classes[name])
        if name in target.funcs:
            return ("func", target.funcs[name])
        return None

    def unique_method(self, name: str) -> Optional[FuncInfo]:
        cands = self.methods_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    # -- transitive closures --------------------------------------------------

    def trans_acquired(self, fi: FuncInfo,
                       _stack: Optional[Set[int]] = None) -> Set[str]:
        key = id(fi)
        if key in self._trans_acq:
            return self._trans_acq[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return set()                   # recursion: already counted above
        stack.add(key)
        out = set(fi.acquires)
        for callee in fi.calls:
            out |= self.trans_acquired(callee, stack)
        stack.discard(key)
        self._trans_acq[key] = out
        return out

    def trans_blocking(self, fi: FuncInfo,
                       _stack: Optional[Set[int]] = None) -> List[tuple]:
        """[(desc, own_lock, chain)] reachable from fi; chain names the
        call path for the witness message."""
        key = id(fi)
        if key in self._trans_blk:
            return self._trans_blk[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return []
        stack.add(key)
        out = [(desc, own, f"{fi.qual}:{line}")
               for line, desc, own in fi.blocking]
        for callee in fi.calls:
            for desc, own, chain in self.trans_blocking(callee, stack):
                out.append((desc, own, f"{fi.qual} -> {chain}"))
        stack.discard(key)
        # one entry per distinct desc keeps messages bounded
        seen, uniq = set(), []
        for desc, own, chain in out:
            if desc not in seen:
                seen.add(desc)
                uniq.append((desc, own, chain))
        self._trans_blk[key] = uniq
        return uniq

    def add_edge(self, src: str, dst: str, rel: str, line: int,
                 qual: str, note: str):
        if src == dst:
            return                         # same-class policy: see docstring
        self.edges.setdefault((src, dst), (rel, line, qual, note))


# ---- pass 1: collect modules, classes, locks, types -------------------------

def _module_rel(modules: Dict[str, ModuleInfo], cur_rel: str,
                node: ast.ImportFrom, name: str) -> Optional[str]:
    """Repo-relative path of the module `name` is imported from (or the
    submodule `name` itself, for `from . import name`)."""
    if node.level:
        base = cur_rel.split("/")[:-1]
        up = node.level - 1
        if up:
            base = base[:-up] if up <= len(base) else []
        parts = base + (node.module.split(".") if node.module else [])
    else:
        if not node.module or not node.module.startswith("spark_rapids_tpu"):
            return None
        parts = node.module.split(".")
    for cand in ("/".join(parts + [name]) + ".py",
                 "/".join(parts + [name, "__init__.py"])):
        if cand in modules:
            return ("submodule", cand)
    for cand in ("/".join(parts) + ".py",
                 "/".join(parts) + "/__init__.py"):
        if cand in modules:
            return ("from", cand)
    return None


def _collect_imports(model: Model, mod: ModuleInfo):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                local = alias.asname or alias.name
                hit = _module_rel(model.modules, mod.rel, node, alias.name)
                if hit is None:
                    continue
                kind, rel = hit
                if kind == "submodule":
                    mod.imports[local] = ("mod", rel)
                else:
                    mod.imports[local] = ("sym", rel, alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if not alias.name.startswith("spark_rapids_tpu"):
                    continue
                local = alias.asname or alias.name.split(".")[0]
                for cand in (alias.name.replace(".", "/") + ".py",
                             alias.name.replace(".", "/") + "/__init__.py"):
                    if cand in model.modules:
                        mod.imports[local] = ("mod", cand)
                        break


def _lock_ctor_kind(value) -> Optional[str]:
    """'lock'/'rlock'/'condition' when `value` constructs one."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func).rsplit(".", 1)[-1]
    if name in _LOCK_CTORS:
        return "rlock" if name == "RLock" else "lock"
    if name == _COND_CTOR:
        return "condition"
    return None


def _collect_module(model: Model, mod: ModuleInfo):
    """Classes, module functions, module-level locks and var types."""
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            ci = ClassInfo(mod.rel, node.name)
            mod.classes[node.name] = ci
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(mod.rel, f"{node.name}.{item.name}",
                                  item, ci, mod)
                    ci.methods[item.name] = fi
                elif isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name):
                    kind = _lock_ctor_kind(item.value)
                    attr = item.targets[0].id
                    if kind in ("lock", "rlock"):
                        ci.locks[attr] = LockDecl(
                            f"{mod.rel}:{node.name}.{attr}", mod.rel,
                            item.value.lineno, kind)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.funcs[node.name] = FuncInfo(mod.rel, node.name, node,
                                            None, mod)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            kind = _lock_ctor_kind(node.value)
            if kind in ("lock", "rlock"):
                mod.module_locks[name] = LockDecl(
                    f"{mod.rel}:{name}", mod.rel, node.value.lineno, kind)
            elif isinstance(node.value, ast.Call):
                mod.var_types[name] = ("ctor", node.value)  # resolved later
    # nested defs inside functions (thread bodies, closures)
    for fi in list(mod.funcs.values()) + [
            m for c in mod.classes.values() for m in c.methods.values()]:
        for sub in ast.walk(fi.node):
            if sub is not fi.node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child = FuncInfo(mod.rel, f"{fi.qual}.<locals>.{sub.name}",
                                 sub, fi.cls, mod)
                fi.locals_funcs[sub.name] = child


def _collect_class_attrs(model: Model, mod: ModuleInfo, ci: ClassInfo):
    """Lock attrs, Condition aliases, and attribute types from every
    `self.X = ...` in the class body (any method, not just __init__)."""
    for fi in ci.methods.values():
        for node in ast.walk(fi.node):
            if not (isinstance(node, (ast.Assign, ast.AnnAssign))):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            for tgt in targets:
                if not (isinstance(tgt, ast.Attribute) and
                        isinstance(tgt.value, ast.Name) and
                        tgt.value.id == "self"):
                    continue
                attr = tgt.attr
                kind = _lock_ctor_kind(value)
                if kind in ("lock", "rlock"):
                    ci.locks.setdefault(attr, LockDecl(
                        f"{ci.rel}:{ci.name}.{attr}", ci.rel,
                        value.lineno, kind))
                    continue
                if kind == "condition":
                    arg = value.args[0] if value.args else None
                    if isinstance(arg, ast.Attribute) and \
                            isinstance(arg.value, ast.Name) and \
                            arg.value.id == "self":
                        ci.aliases[attr] = arg.attr
                    else:
                        # bare Condition(): its own (internal) lock
                        ci.locks.setdefault(attr, LockDecl(
                            f"{ci.rel}:{ci.name}.{attr}", ci.rel,
                            value.lineno, "condition"))
                    continue
                t = None
                if value is not None:
                    t = _value_type(model, mod, ci, value)
                if t is None and isinstance(node, ast.AnnAssign):
                    t = _ann_type(model, mod, node.annotation)
                if t is not None:
                    ci.attr_types.setdefault(attr, t)


def _ann_type(model: Model, mod: ModuleInfo, ann) -> Optional[tuple]:
    """TypeRef from an annotation: ('class', ClassInfo) | ('seq', T) |
    ('map', T) | ('queue',)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        ci = model.resolve_class(mod, ann.id)
        return ("class", ci) if ci is not None else None
    if isinstance(ann, ast.Attribute):
        if _dotted(ann) == "queue.Queue":
            return ("queue",)
        ci = model.resolve_class(mod, ann.attr)
        return ("class", ci) if ci is not None else None
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value).rsplit(".", 1)[-1]
        sl = ann.slice
        if base in ("List", "Set", "FrozenSet", "Sequence", "Iterable",
                    "Iterator", "Tuple", "list", "set", "tuple"):
            elt = sl.elts[0] if isinstance(sl, ast.Tuple) and sl.elts else sl
            t = _ann_type(model, mod, elt)
            return ("seq", t) if t is not None else None
        if base in ("Dict", "Mapping", "MutableMapping", "dict"):
            if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                t = _ann_type(model, mod, sl.elts[1])
                return ("map", t) if t is not None else None
            return None
        if base == "Optional":
            return _ann_type(model, mod, sl)
    return None


def _value_type(model: Model, mod: ModuleInfo, ci: Optional[ClassInfo],
                value) -> Optional[tuple]:
    """TypeRef of a constructor-call value (no local env — used for
    attribute assignments): `Foo(...)`, `mod_alias.Foo(...)`,
    `queue.Queue(...)`."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Name):
        target = model.resolve_class(mod, f.id)
        if target is not None:
            return ("class", target)
    elif isinstance(f, ast.Attribute):
        if _dotted(f) == "queue.Queue":
            return ("queue",)
        if isinstance(f.value, ast.Name):
            hit = model.resolve_in_alias(mod, f.value.id, f.attr)
            if hit is not None and hit[0] == "class":
                return ("class", hit[1])
    return None


# ---- pass 2: per-function scan ----------------------------------------------

class _FuncScanner:
    """One function's walk: tracks the held-lock stack through `with`
    regions and a forward-only local type environment, recording direct
    acquisitions, resolved calls, blocking ops, and isolation reaches."""

    _SEQ_CTORS = {"list", "sorted", "set", "tuple", "frozenset", "reversed"}
    _ELEM_PICKERS = {"min", "max", "next"}

    def __init__(self, model: Model, fi: FuncInfo):
        self.model = model
        self.fi = fi
        self.env: Dict[str, tuple] = dict(fi.param_types)
        if fi.cls is not None:
            self.env.setdefault("self", ("class", fi.cls))

    def run(self):
        node = self.fi.node
        self._scan_stmts(node.body, [])

    # -- type environment -----------------------------------------------------

    def _type_of(self, expr) -> Optional[tuple]:
        model, mod = self.model, self.fi.mod
        if isinstance(expr, ast.Name):
            t = self.env.get(expr.id)
            if t is not None:
                return t
            vt = mod.var_types.get(expr.id)
            if vt is not None and vt[0] == "ctor":
                return _value_type(model, mod, self.fi.cls, vt[1])
            return None
        if isinstance(expr, ast.Attribute):
            base_t = self._type_of(expr.value)
            if base_t is not None and base_t[0] == "class":
                return base_t[1].attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            base_t = self._type_of(expr.value)
            if base_t is not None and base_t[0] in ("seq", "map"):
                return base_t[1]
            return None
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            saved = dict(self.env)
            try:
                for comp in expr.generators:
                    self._bind_for_target(comp.target, comp.iter)
                t = self._type_of(expr.elt)
            finally:
                self.env = saved
            return ("seq", t) if t is not None else None
        if isinstance(expr, ast.IfExp):
            return self._type_of(expr.body) or self._type_of(expr.orelse)
        if isinstance(expr, ast.Call):
            return self._call_type(expr)
        return None

    def _call_type(self, call) -> Optional[tuple]:
        model, mod = self.model, self.fi.mod
        f = call.func
        if isinstance(f, ast.Name):
            ci = model.resolve_class(mod, f.id)
            if ci is not None:
                return ("class", ci)
            if f.id in self._SEQ_CTORS and call.args:
                t = self._type_of(call.args[0])
                return t if t is not None and t[0] == "seq" else None
            if f.id in self._ELEM_PICKERS and call.args:
                t = self._type_of(call.args[0])
                if t is not None and t[0] == "seq":
                    return t[1]
                return None
            fn = model.resolve_func(mod, f.id)
            if fn is not None:
                return fn.ret_type
            return None
        if isinstance(f, ast.Attribute):
            if _dotted(f) == "queue.Queue":
                return ("queue",)
            base_t = self._type_of(f.value)
            if base_t is not None:
                if base_t[0] == "map" and f.attr in ("get", "pop",
                                                     "setdefault"):
                    return base_t[1]
                if base_t[0] == "map" and f.attr == "values":
                    return ("seq", base_t[1])
                if base_t[0] == "class":
                    meth = base_t[1].methods.get(f.attr)
                    if meth is not None:
                        return meth.ret_type
            if isinstance(f.value, ast.Name):
                hit = model.resolve_in_alias(mod, f.value.id, f.attr)
                if hit is not None and hit[0] == "class":
                    return ("class", hit[1])
        return None

    def _bind_for_target(self, target, iter_expr):
        t = self._type_of(iter_expr)
        if isinstance(target, ast.Name) and t is not None and t[0] == "seq":
            self.env[target.id] = t[1]

    # -- lock identification --------------------------------------------------

    def _lock_of(self, expr) -> Optional[LockDecl]:
        cls = self.fi.cls
        if isinstance(expr, ast.Name):
            return self.fi.mod.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and cls is not None:
                attr = cls.aliases.get(expr.attr, expr.attr)
                return cls.locks.get(attr)
            base_t = self._type_of(expr.value)
            if base_t is not None and base_t[0] == "class":
                owner = base_t[1]
                attr = owner.aliases.get(expr.attr, expr.attr)
                return owner.locks.get(attr)
        return None

    # -- call resolution ------------------------------------------------------

    def _resolve_call(self, call) -> Optional[FuncInfo]:
        model, mod, fi = self.model, self.fi.mod, self.fi
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in fi.locals_funcs:
                return fi.locals_funcs[f.id]
            ci = model.resolve_class(mod, f.id)
            if ci is not None:
                return ci.methods.get("__init__")
            return model.resolve_func(mod, f.id)
        if isinstance(f, ast.Attribute):
            meth = f.attr
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" and \
                    fi.cls is not None and meth in fi.cls.methods:
                return fi.cls.methods[meth]
            base_t = self._type_of(base)
            if base_t is not None and base_t[0] == "class":
                hit = base_t[1].methods.get(meth)
                if hit is not None:
                    return hit
            if isinstance(base, ast.Name):
                hit = model.resolve_in_alias(mod, base.id, meth)
                if hit is not None:
                    return (hit[1].methods.get("__init__")
                            if hit[0] == "class" else hit[1])
            if base_t is None:
                # unique-name fallback: sound only because a wrong pick
                # is audited by the dynamic witness divergence check
                return model.unique_method(meth)
        return None

    # -- blocking classification ----------------------------------------------

    def _blocking_desc(self, call) -> Optional[Tuple[str, Optional[str]]]:
        """(description, own-lock-name) when `call` is an unbounded
        blocking op. own-lock is the condition's underlying lock for
        `.wait()` (exempt when it is the only lock held)."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        name = f.attr
        kwargs = {k.arg for k in call.keywords}
        bounded = "timeout" in kwargs or bool(call.args)
        if name == "wait" and not bounded:
            own = self._lock_of(f.value)
            return ("wait() without timeout", own.name if own else None)
        if name == "join" and not bounded and not call.args:
            return ("join() without timeout", None)
        if name == "result" and not bounded:
            return ("result() without timeout", None)
        if name in ("get", "put"):
            t = self._type_of(f.value)
            if t == ("queue",):
                if "timeout" in kwargs or "block" in kwargs:
                    return None
                return (f"queue.Queue.{name}() without timeout", None)
            return None
        if name == "execute":
            t = self._type_of(f.value)
            is_exec = (t is not None and t[0] == "class" and
                       t[1].name == "PlanExecutor")
            if not is_exec and isinstance(f.value, ast.Attribute):
                is_exec = f.value.attr == "executor"
            if is_exec:
                return ("PlanExecutor.execute (whole-plan execution)", None)
        return None

    # -- statement walk -------------------------------------------------------

    def _scan_stmts(self, stmts, held: List[LockDecl]):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                entered = 0
                for item in st.items:
                    decl = self._lock_of(item.context_expr)
                    if decl is not None:
                        for h in held:
                            self.model.add_edge(
                                h.name, decl.name, self.fi.rel,
                                item.context_expr.lineno, self.fi.qual,
                                "nested with")
                        self.fi.acquires.add(decl.name)
                        held.append(decl)
                        entered += 1
                    else:
                        self._walk_expr(item.context_expr, held)
                self._scan_stmts(st.body, held)
                for _ in range(entered):
                    held.pop()
                continue
            if isinstance(st, ast.Assign):
                self._walk_expr(st.value, held)
                if len(st.targets) == 1 and isinstance(st.targets[0],
                                                       ast.Name):
                    t = self._type_of(st.value)
                    if t is not None:
                        self.env[st.targets[0].id] = t
                for tgt in st.targets:
                    self._walk_expr(tgt, held)
                continue
            if isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    self._walk_expr(st.value, held)
                if isinstance(st.target, ast.Name):
                    t = (self._type_of(st.value) if st.value is not None
                         else None) or _ann_type(self.model, self.fi.mod,
                                                 st.annotation)
                    if t is not None:
                        self.env[st.target.id] = t
                self._walk_expr(st.target, held)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._walk_expr(st.iter, held)
                self._bind_for_target(st.target, st.iter)
                self._scan_stmts(st.body, held)
                self._scan_stmts(st.orelse, held)
                continue
            if isinstance(st, ast.If):
                self._walk_expr(st.test, held)
                self._scan_stmts(st.body, held)
                self._scan_stmts(st.orelse, held)
                continue
            if isinstance(st, ast.While):
                self._walk_expr(st.test, held)
                self._scan_stmts(st.body, held)
                self._scan_stmts(st.orelse, held)
                continue
            if isinstance(st, ast.Try):
                self._scan_stmts(st.body, held)
                for h in st.handlers:
                    self._scan_stmts(h.body, held)
                self._scan_stmts(st.orelse, held)
                self._scan_stmts(st.finalbody, held)
                continue
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._walk_expr(child, held)

    # -- expression walk ------------------------------------------------------

    def _walk_expr(self, expr, held: List[LockDecl]):
        if expr is None:
            return
        if isinstance(expr, ast.Call):
            self._handle_call(expr, held)
            return
        if isinstance(expr, ast.Attribute):
            self._check_isolation(expr)
            node = expr.value
            while isinstance(node, ast.Attribute):
                node = node.value          # the chain was checked whole
            self._walk_expr(node, held)
            return
        if isinstance(expr, ast.Lambda):
            self._walk_expr(expr.body, held)
            return
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._walk_expr(child, held)
            elif isinstance(child, ast.comprehension):
                self._walk_expr(child.iter, held)
                for cond in child.ifs:
                    self._walk_expr(cond, held)

    def _handle_call(self, call, held: List[LockDecl]):
        callee = self._resolve_call(call)
        block = self._blocking_desc(call)
        if callee is not None:
            self.fi.calls.add(callee)
        if block is not None:
            self.fi.blocking.append((call.lineno, block[0], block[1]))
        if held:
            self.fi.under.append((tuple(h.name for h in held), call.lineno,
                                  callee, block))
        if isinstance(call.func, ast.Attribute):
            self._check_isolation(call.func)
            node = call.func.value
            while isinstance(node, ast.Attribute):
                node = node.value          # the chain was checked whole
            self._walk_expr(node, held)
        elif not isinstance(call.func, ast.Name):
            self._walk_expr(call.func, held)
        # lambda args: min/max/sorted/filter/map key functions see the
        # sequence's element type
        elem = None
        fname = call.func.id if isinstance(call.func, ast.Name) else ""
        if fname in ("min", "max", "sorted", "filter", "map") and call.args:
            for a in call.args:
                t = self._type_of(a)
                if t is not None and t[0] == "seq":
                    elem = t[1]
                    break
        for a in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(a, ast.Lambda) and elem is not None:
                saved = dict(self.env)
                for p in a.args.args:
                    self.env[p.arg] = elem
                self._walk_expr(a.body, held)
                self.env = saved
            else:
                self._walk_expr(a, held)

    # -- worker isolation -----------------------------------------------------

    def _check_isolation(self, attr_node):
        """Unrolls the full attribute chain once (callers recurse only
        into the base) and applies the FleetWorker reach policy."""
        attrs: List[str] = []
        node = attr_node
        while isinstance(node, ast.Attribute):
            attrs.append(node.attr)
            node = node.value
        attrs.reverse()
        base_t = self._type_of(node)
        if base_t is None or base_t[0] != "class":
            return
        policy = _ISOLATION.get(base_t[1].name)
        if policy is None:
            return
        if self.fi.cls is not None and self.fi.cls.name == base_t[1].name:
            return                          # the worker touching itself
        head = attrs[0]
        if head in policy["surface"]:
            return
        if head in policy["via"]:
            if len(attrs) >= 2 and attrs[1] in policy["via"][head]:
                return
            reach = ".".join(attrs)
            self.model.findings.append(Finding(
                "worker-isolation", self.fi.rel, attr_node.lineno,
                self.fi.qual,
                f"reaches worker-internal state `{reach}` — "
                f"{base_t[1].name}.{head} only admits "
                f"{sorted(policy['via'][head])} from outside the worker"))
            return
        if head in policy["owned"]:
            reach = ".".join(attrs)
            self.model.findings.append(Finding(
                "worker-isolation", self.fi.rel, attr_node.lineno,
                self.fi.qual,
                f"reaches {base_t[1].name}-owned mutable state `{reach}` "
                f"outside the owning worker (allowed surface: "
                f"{sorted(policy['surface'])})"))


# ---- pass 3: interprocedural edges + blocking findings ----------------------

def _finalize(model: Model):
    all_funcs: List[FuncInfo] = []
    for mod in model.modules.values():
        all_funcs.extend(mod.funcs.values())
        for ci in mod.classes.values():
            all_funcs.extend(ci.methods.values())
        for fi in list(all_funcs):
            all_funcs.extend(fi.locals_funcs.values())
    # dedupe (locals may be reachable from two lists)
    seen: Set[int] = set()
    funcs = []
    for fi in all_funcs:
        if id(fi) not in seen:
            seen.add(id(fi))
            funcs.append(fi)

    for fi in funcs:
        for held_names, line, callee, block in fi.under:
            if callee is not None:
                for dst in model.trans_acquired(callee):
                    for src in held_names:
                        model.add_edge(src, dst, fi.rel, line, fi.qual,
                                       f"via {callee.qual}")
            # blocking at the call site itself
            if block is not None:
                desc, own = block
                others = [h for h in held_names if h != own]
                if others:
                    model.findings.append(Finding(
                        "blocking-under-lock", fi.rel, line, fi.qual,
                        f"{desc} while holding "
                        f"{', '.join(_short(h) for h in others)}"))
            elif callee is not None:
                for desc, own, chain in model.trans_blocking(callee):
                    others = [h for h in held_names if h != own]
                    if others:
                        model.findings.append(Finding(
                            "blocking-under-lock", fi.rel, line, fi.qual,
                            f"call chain reaches {desc} "
                            f"({chain}) while holding "
                            f"{', '.join(_short(h) for h in others)}"))


def _find_cycles(model: Model, declared: List[Tuple[str, str]]):
    adj: Dict[str, Dict[str, tuple]] = {}
    for (src, dst), wit in model.edges.items():
        adj.setdefault(src, {})[dst] = wit
    for src, dst in declared:
        if src != dst:
            adj.setdefault(src, {}).setdefault(
                dst, ("<allowlist>", 0, "declared-edge", "declared"))

    index_counter = [0]
    stack: List[str] = []
    on_stack: Set[str] = set()
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    sccs: List[List[str]] = []

    def strongconnect(v: str):
        work = [(v, iter(sorted(adj.get(v, {}))))]
        index[v] = low[v] = index_counter[0]
        index_counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, {})))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        comp_set = set(comp)
        start = min(comp)
        # walk one concrete cycle inside the SCC for the witness
        path = [start]
        cur = start
        while True:
            nxt = min(d for d in adj.get(cur, {}) if d in comp_set)
            if nxt == start or nxt in path:
                path.append(nxt)
                break
            path.append(nxt)
            cur = nxt
        lines = []
        for a, b in zip(path, path[1:]):
            rel, line, qual, note = adj[a][b]
            lines.append(f"{_short(a)} -> {_short(b)} "
                         f"[{qual} at {rel}:{line}, {note}]")
        first = adj[path[0]][path[1]]
        model.findings.append(Finding(
            "lock-order-cycle", first[0], first[1],
            " -> ".join(_short(n) for n in path),
            "lock-order cycle (potential deadlock): " + "; ".join(lines)))


# ---- driver -----------------------------------------------------------------

def build_model(paths: List[str], repo_root: str) -> Model:
    model = Model()
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, _, names in os.walk(p):
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
    parsed = []
    for path in sorted(files):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        with open(path, "rb") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            model.findings.append(Finding("parse-error", rel,
                                          e.lineno or 0, "", str(e)))
            continue
        parsed.append(model.add_module(rel, tree))
    for mod in parsed:
        _collect_module(model, mod)
    for mod in parsed:
        _collect_imports(model, mod)
    model.index()
    for mod in parsed:
        for ci in mod.classes.values():
            _collect_class_attrs(model, mod, ci)
            for decl in ci.locks.values():
                model.locks[decl.name] = decl
    # param/return annotations need classes indexed first
    for mod in parsed:
        every = list(mod.funcs.values()) + [
            m for c in mod.classes.values() for m in c.methods.values()]
        for fi in every:
            every.extend(fi.locals_funcs.values())
        for fi in every:
            args = fi.node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                t = _ann_type(model, mod, a.annotation)
                if t is not None:
                    fi.param_types[a.arg] = t
            fi.ret_type = _ann_type(model, mod, fi.node.returns)
    # `self.X = param` propagates the parameter's annotated type to the
    # attribute (e.g. SpillableBuffer.__init__'s `self._pool = pool`).
    # Ctor-call values were typed in _collect_class_attrs, but that pass
    # runs before parameter annotations resolve.
    for mod in parsed:
        for ci in mod.classes.values():
            for fi in ci.methods.values():
                for node in ast.walk(fi.node):
                    if not (isinstance(node, ast.Assign) and
                            isinstance(node.value, ast.Name)):
                        continue
                    t = fi.param_types.get(node.value.id)
                    if t is None:
                        continue
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute) and
                                isinstance(tgt.value, ast.Name) and
                                tgt.value.id == "self" and
                                tgt.attr not in ci.locks and
                                tgt.attr not in ci.aliases):
                            ci.attr_types.setdefault(tgt.attr, t)
    for mod in parsed:
        every = list(mod.funcs.values()) + [
            m for c in mod.classes.values() for m in c.methods.values()]
        for fi in every:
            every.extend(fi.locals_funcs.values())
        for fi in every:
            _FuncScanner(model, fi).run()
    _finalize(model)
    return model


def load_allowlist(path: str):
    """-> ({(path, rule, context): justification}, [(src, dst)] declared
    edges). Every entry REQUIRES a non-empty `# justification`."""
    out: Dict[Tuple[str, str, str], str] = {}
    declared: List[Tuple[str, str]] = []
    if not os.path.exists(path):
        return out, declared
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entry, _, just = line.partition("#")
            just = just.strip()
            fields = [p.strip() for p in entry.strip().split("::")]
            if not just:
                raise SystemExit(
                    f"{path}:{lineno}: allowlist entry has no "
                    "justification — every vetted exception must say why")
            if len(fields) == 2 and fields[0] == "edge":
                src, sep, dst = fields[1].partition("->")
                if not sep or not src.strip() or not dst.strip():
                    raise SystemExit(
                        f"{path}:{lineno}: malformed edge declaration "
                        "(want edge::<lock> -> <lock>  # justification)")
                declared.append((src.strip(), dst.strip()))
                continue
            if len(fields) != 3 or not all(fields):
                raise SystemExit(
                    f"{path}:{lineno}: malformed allowlist entry "
                    f"(want path::rule::context  # justification, or "
                    "edge::<lock> -> <lock>  # justification)")
            out[tuple(fields)] = just
    return out, declared


def default_allowlist_path() -> str:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(repo_root, "tools", "lint_concurrency_allowlist.txt")


def build_graph_json(paths: Optional[List[str]] = None,
                     repo_root: Optional[str] = None,
                     allowlist: Optional[str] = None) -> Dict:
    """The shared static/dynamic edge vocabulary: lock name ->
    construction site, plus every derived and declared edge. This is
    what runtime/lockdep.py loads to match observed edges back to
    their static prediction."""
    if repo_root is None:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
    if paths is None:
        paths = [os.path.join(repo_root, "spark_rapids_tpu")]
    _, declared = load_allowlist(allowlist or default_allowlist_path())
    model = build_model(paths, repo_root)
    edges = sorted(set(model.edges) | {e for e in declared
                                       if e[0] != e[1]})
    return {
        "locks": {name: decl.site
                  for name, decl in sorted(model.locks.items())},
        "edges": [list(e) for e in edges],
        "declared": [list(e) for e in sorted(set(declared))],
    }


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(
        description="Concurrency linter: lock-order graph, "
                    "blocking-under-lock, worker isolation "
                    "(docs/analysis.md#concurrency-invariants)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: spark_rapids_tpu)")
    ap.add_argument("--allowlist", default=default_allowlist_path())
    ap.add_argument("--list", action="store_true",
                    help="print every finding, including allowlisted")
    ap.add_argument("--emit-graph", metavar="FILE",
                    help="write the lock graph JSON (lock name -> "
                         "construction site, edges) to FILE ('-' for "
                         "stdout) and exit")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(repo_root, "spark_rapids_tpu")]
    allow, declared = load_allowlist(args.allowlist)
    if args.emit_graph:
        graph = build_graph_json(paths, repo_root, args.allowlist)
        text = json.dumps(graph, indent=2, sort_keys=True)
        if args.emit_graph == "-":
            print(text)
        else:
            with open(args.emit_graph, "w") as f:
                f.write(text + "\n")
        return 0
    model = build_model(paths, repo_root)
    _find_cycles(model, declared)
    findings = sorted(model.findings,
                      key=lambda f: (f.path, f.line, f.rule, f.context))
    used: Set[Tuple[str, str, str]] = set()
    open_findings: List[Finding] = []
    emitted: Set[tuple] = set()
    for f in findings:
        dedup = (f.key(), f.message)
        if dedup in emitted:
            continue
        emitted.add(dedup)
        if f.key() in allow:
            used.add(f.key())
            if args.list:
                print(f"ALLOWED {f}  # {allow[f.key()]}")
        else:
            open_findings.append(f)
    for f in open_findings:
        print(f)
    stale = set(allow) - used
    for key in sorted(stale):
        print(f"STALE allowlist entry (matches no finding — prune it): "
              f"{'::'.join(key)}")
    if open_findings or stale:
        print(f"lint_concurrency: {len(open_findings)} finding(s), "
              f"{len(stale)} stale allowlist entr(ies) "
              f"({len(used)} allowlisted; "
              f"{len(model.edges)} lock-order edge(s))")
        return 1
    print(f"lint_concurrency: clean ({len(used)} vetted exception(s), "
          f"{len(model.locks)} lock class(es), "
          f"{len(model.edges)} lock-order edge(s), "
          f"{len(declared)} declared edge(s))")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
