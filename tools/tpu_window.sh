#!/usr/bin/env bash
# One-shot TPU capture: run EVERYTHING that needs the real chip, the moment
# a tunnel window opens. This is the standing answer to the round-4/5
# verdict items that are tunnel-gated (on-chip test tier, bench detail with
# %-of-roofline, the relational A/B on device, parse_uri viability, the
# primitive sweep, the row-conversion word kernels):
#
#     ./tools/tpu_window.sh          # probes first; exits 75 if tunnel dead
#
# Artifacts land in tools/*.jsonl + BENCH_DETAIL_TPU.md + the tpu-smoke log;
# commit them.
set -uo pipefail
cd "$(dirname "$0")/.."

python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_tunnel
st = probe_tunnel()
print(f"tunnel: {st}")
sys.exit(0 if st != "dead" else 75)
EOF
rc=$?
if [ $rc -eq 75 ]; then
    echo "tunnel dead - nothing to capture (exit 75)"
    exit 75
elif [ $rc -ne 0 ]; then
    # probe itself broke (import error, env) - NOT the retryable no-window
    # condition; surface it so automation doesn't retry forever
    echo "tunnel probe FAILED rc=$rc (not a dead tunnel)" >&2
    exit $rc
fi

set -x
fail=0
# 1. on-chip correctness tier: one config per op family (24 node ids)
./ci/tpu-smoke.sh 2>&1 | tee tools/tpu_smoke_capture.log || fail=1

# 2. full bench detail on device (un-pinned), with %-of-roofline context
python tools/capture_bench_detail.py || fail=1

# 3. relational A/B on device: the number the round-4 redesign is owed
python tools/ab_relational.py --scale 1.0 --iters 5 --device || fail=1

# 4. primitive sweep on device (refreshes the r2 figures the kernel
#    docstrings cite)
python tools/tpu_primitives.py --iters 5 || fail=1

# 5. parse_uri viability at 52k rows (VERDICT Missing #3): small-shape
#    first so a number exists even if the big shape times out
python benchmarks/bench_parse_uri.py --scale 0.0005 --iters 3 \
    | tee -a tools/tpu_parse_uri.jsonl || fail=1
python benchmarks/bench_parse_uri.py --scale 0.005 --iters 3 \
    | tee -a tools/tpu_parse_uri.jsonl || fail=1

# 6. row-conversion word-kernel A/B on device — one file per kernel so the
#    records stay attributable (run_config emits no kernel field)
SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL=word \
    python benchmarks/bench_row_conversion.py --scale 0.2 --iters 5 \
    | tee -a tools/tpu_row_conversion_word.jsonl || fail=1
SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL=concat \
    python benchmarks/bench_row_conversion.py --scale 0.2 --iters 5 \
    | tee -a tools/tpu_row_conversion_concat.jsonl || fail=1

# 7. headline
python bench.py || fail=1
set +x
[ $fail -eq 0 ] && echo "TPU WINDOW CAPTURE COMPLETE" || echo "TPU WINDOW CAPTURE: some steps failed (see above)"
exit $fail
