"""Capture the staged-config benchmarks into BENCH_DETAIL.md.

Runs every micro-bench (benchmarks/run_all.py's set) in a child process the
parent can time out — the TPU backend on this image can hang at init
(bench.py learned the same lesson) — and writes the parsed records plus a
roofline note per op into BENCH_DETAIL.md with the backend clearly marked.

Usage:
    python tools/capture_bench_detail.py             # full scale
    python tools/capture_bench_detail.py --scale 0.01 --cpu   # smoke
"""
import argparse
import datetime
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCHES = [
    ("row_conversion", "benchmarks/bench_row_conversion.py",
     "HBM-bandwidth bound: one bitcast + concatenate per direction; "
     "bytes/s is the roofline metric"),
    ("groupby", "benchmarks/bench_groupby.py",
     "lax.sort bound (multi-operand sort + cumsum spans, scatter-free)"),
    ("join", "benchmarks/bench_join.py",
     "three lax.sort passes (union rank + two span sorts); "
     "searchsorted-free"),
    ("parquet_read", "benchmarks/bench_parquet_read.py",
     "host decode (native C++) + device_put; decompression bound"),
    ("cast_string_to_float", "benchmarks/bench_cast_string_to_float.py",
     "VPU elementwise over the padded char matrix"),
    ("bloom_filter", "benchmarks/bench_bloom_filter.py",
     "hash (VPU) + sorted-scatter bit set; scatter is the ceiling"),
    ("parse_uri", "benchmarks/bench_parse_uri.py",
     "VPU class-table lookups over padded chars"),
    ("nds_q3", "benchmarks/bench_nds_q3.py",
     "end-to-end star join -> multi-key groupby -> order-by; "
     "lax.sort bound through the joins and groupby"),
    ("partition", "benchmarks/bench_partition.py",
     "A/B: sort+searchsorted vs streaming compare-reduce vs pallas "
     "histogram — the shuffle bucket-map decision"),
]
TIMEOUT_S = 600


def run_bench(path: str, scale: float, iters: int, cpu: bool):
    code = (
        "import jax\n"
        + ("jax.config.update('jax_platforms', 'cpu')\n" if cpu else "")
        + "import runpy, sys\n"
        + f"sys.argv = ['bench', '--scale', '{scale}', '--iters', '{iters}']\n"
        + f"runpy.run_path({path!r}, run_name='__main__')\n")
    try:
        p = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                           capture_output=True, text=True, timeout=TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return None, "timed out (backend hang?)"
    recs = []
    for line in p.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "bench" in rec and "ms" in rec:
                recs.append(rec)
    if p.returncode != 0 and not recs:
        return None, p.stderr.strip()[-300:]
    if p.returncode != 0:
        # partial sweep: keep what measured, but mark the truncation so the
        # table is never mistaken for a full capture
        return recs, f"bench exited rc={p.returncode} mid-sweep: " \
                     f"{p.stderr.strip()[-200:]}"
    return recs, None


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (tunnel down / smoke)")
    ap.add_argument("--out", default=os.path.join(ROOT, "BENCH_DETAIL.md"))
    args = ap.parse_args(argv)

    if not args.cpu:
        # Fail fast on a dead tunnel (<5 s) instead of burning a 600 s
        # timeout per bench file — same healthz probe as ci/tpu-smoke.sh /
        # bench.py; exit 75 = EX_TEMPFAIL (infrastructure, not a regression).
        sys.path.insert(0, ROOT)
        from bench import probe_tunnel
        health = probe_tunnel()
        if health == "dead" and os.environ.get("SRT_BENCH_FORCE_DEVICE", "") != "1":
            print("capture_bench_detail: axon tunnel healthz dead — refusing "
                  "an unpinned capture (it would hang). Re-run with --cpu for "
                  "a CPU capture, or SRT_BENCH_FORCE_DEVICE=1 to override.",
                  file=sys.stderr)
            sys.exit(75)

    backend = "cpu (pinned)" if args.cpu else "default (TPU when up)"
    lines = [
        "# BENCH_DETAIL — staged-config measurements",
        "",
        f"Captured {datetime.date.today()} · backend: {backend} · "
        f"scale {args.scale} · {args.iters} iters/steady-state.",
        "Records are `benchmarks/*` JSON lines (nvbench-equivalent harness,",
        "SURVEY.md §2.3); rows/s computed over the config's num_rows.",
        "",
    ]
    if args.cpu:
        lines += [
            "> **Status:** CPU-pinned capture (the axon TPU tunnel hangs at",
            "> backend init — see PARITY.md). Re-run this tool WITHOUT",
            "> `--cpu` at full scale when the chip is reachable; the numbers",
            "> below establish the harness and the relative A/B shape only.",
            "> Pallas interpret-mode rows are meaningless off-chip by design.",
            "",
        ]
    for name, path, roofline in BENCHES:
        print(f"== {name}", flush=True)
        recs, err = run_bench(path, args.scale, args.iters, args.cpu)
        lines.append(f"## {name}")
        lines.append("")
        lines.append(f"Roofline: {roofline}.")
        lines.append("")
        if err and not recs:
            lines.append(f"**capture failed:** {err}")
            lines.append("")
            continue
        if err:
            lines.append(f"**PARTIAL capture** — {err}")
            lines.append("")
        lines.append("| bench | axes | ms | rows/s |")
        lines.append("|---|---|---|---|")
        for r in recs:
            rps = r.get("rows_per_s")
            rps = f"{rps:,}" if isinstance(rps, (int, float)) else "—"
            lines.append(f"| {r.get('bench')} | `{r.get('axes')}` | "
                         f"{r.get('ms')} | {rps} |")
        lines.append("")
    with open(args.out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
